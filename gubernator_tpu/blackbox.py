"""Incident black box: triggered capture bundles + deterministic replay.

The observability fabric (tracing/saturation/audit/profiling) can
*detect* every failure class the flight recorder dumps on — but the
dump is a log line of spans, and the traffic that caused the incident
evaporates with the moment.  This module turns the GUBC wire choke
points (wire.py kinds 1-7: every byte the daemons exchange flows
through a handful of encode/decode sites) into an always-on bounded
**traffic tap**, and every flight-recorder auto-dump trigger into a
crash-safe on-disk **incident bundle** that `scripts/replay.py` can
re-drive deterministically.

Three pieces:

* **Taps** — per-wire byte-budgeted in-memory rings (public / peer /
  global / transfer / region, classified from the frame's kind byte).
  `tap()` records (wall ns, mono ns, direction, peer, kind, raw frame
  bytes); `tap_taken()` reconstructs the kind-5 frames a native-edge
  take batch coalesced (the one choke point that no longer holds the
  original bytes).  Disabled (`GUBER_BLACKBOX=0` or force_disable) the
  tap is one branch per frame — bench-gated like tracing/profiling
  (blackbox_overhead_ratio >= 0.95).

* **Bundles** — `on_trigger` rides tracing.Recorder.dump_hooks: every
  _DUMP_KINDS event (plus POST /debug/incident) wakes an off-thread
  writer that coalesces trigger storms (one bundle, many trigger
  records), rate-limits (min_interval_s), freezes the rings, and
  writes a temp+fsync+rename bundle directory: manifest (triggers,
  stamps, version, knobs, ring fingerprints, fault seed, per-file
  CRCs), per-wire `.gfl` frame logs, span/event snapshots, the
  /debug/status|latency|audit|tenants docs, a metrics scrape, and —
  when the durability plane has one — the state snapshot.  Retention
  is bounded (GUBER_BLACKBOX_RETAIN oldest-pruned).

* **Loader** — `load_bundle()` is the ONE parser replay and
  scripts/blackbox_fsck.py share: manifest format/version, per-file
  CRC32 + size, frame-log header and per-record CRC all verify before
  a single frame is surfaced, so a corrupt bundle can never
  half-replay (BundleError, loudly).

Capture scope: GUBC frames only.  JSON bodies and gRPC protobuf peers
are not tapped (the columnar wire IS the steady-state data plane); the
native express queue answers NO_BATCHING singles entirely in C++ and
those frames never surface to Python — both are documented replay
slack (architecture.md "Incident black box").
"""

from __future__ import annotations

import itertools
import json
import os
import shutil
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .utils.logging import category_logger

logger = category_logger("blackbox")

# ---------------------------------------------------------------------
# Process-wide switches (the tracing/profiling plane pattern): the
# daemon applies its parsed GUBER_BLACKBOX via set_enabled; library
# embedders get the import-time env default (on).  force_disable is
# the bench's "compiled out" baseline for the overhead gate.
# ---------------------------------------------------------------------
_FORCE_DISABLED: bool = False


def _env_enabled(default: bool = True) -> bool:
    v = os.environ.get("GUBER_BLACKBOX", "").strip().lower()
    if not v:
        return default
    return v not in ("0", "false", "off", "no")


_ENABLED: bool = _env_enabled()


def set_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


def force_disable(flag: bool) -> None:
    """Bench hook: behave as if the module did not exist (the
    'blackbox-compiled-out' baseline of the overhead gate)."""
    global _FORCE_DISABLED
    _FORCE_DISABLED = bool(flag)


def enabled() -> bool:
    """One branch — the hot-path guard every tap uses."""
    return _ENABLED and not _FORCE_DISABLED


# ---------------------------------------------------------------------
# Wire classification + frame-log codec
# ---------------------------------------------------------------------
#: The five capture rings, one per wire plane; classification is the
#: frame's kind byte (raw[5]) — the same sniff the gateway routes by.
WIRES = ("public", "peer", "global", "transfer", "region")
_KIND_WIRE = {1: "peer", 2: "peer", 3: "global", 4: "transfer",
              5: "public", 6: "public", 7: "region"}

_GUBC_MAGIC = b"GUBC"

#: Frame-log file format: `GUBL | u32 version`, then per record
#: `u32 payload_len | u32 crc32(payload) | payload` where payload is
#: `<QQBBHI` wall_ns, mono_ns, direction (0=in 1=out), kind, peer_len,
#: frame_len, followed by the peer string and the raw frame bytes.
#: Length+CRC per record means truncation and bit flips both reject at
#: the exact record, never as a silently shorter capture.
GFL_MAGIC = b"GUBL"
GFL_VERSION = 1
_REC_HEAD = struct.Struct("<QQBBHI")

BUNDLE_FORMAT = "gubernator-blackbox-bundle"
BUNDLE_VERSION = 1
MANIFEST_NAME = "manifest.json"

#: (wall_ns, mono_ns, direction "in"/"out", peer, kind, frame bytes)
FrameRecord = Tuple[int, int, str, str, int, bytes]


class BundleError(Exception):
    """A bundle failed verification — corrupt, truncated, or from an
    incompatible version.  Loaders raise instead of degrading: a
    half-verified bundle must never half-replay."""


def encode_frame_log(records: List[FrameRecord]) -> bytes:
    parts = [GFL_MAGIC, struct.pack("<I", GFL_VERSION)]
    for wall_ns, mono_ns, direction, peer, kind, frame in records:
        peer_b = peer.encode("utf-8")
        payload = (
            _REC_HEAD.pack(wall_ns, mono_ns,
                           0 if direction == "in" else 1,
                           kind, len(peer_b), len(frame))
            + peer_b + frame
        )
        parts.append(struct.pack("<II", len(payload), zlib.crc32(payload)))
        parts.append(payload)
    return b"".join(parts)


def decode_frame_log(raw: bytes, name: str = "frame log"
                     ) -> List[FrameRecord]:
    """Parse one .gfl file; BundleError on any malformation (wrong
    magic/version, truncated record, CRC mismatch, trailing bytes)."""
    if raw[:4] != GFL_MAGIC:
        raise BundleError(f"{name}: bad magic (not a GUBL frame log)")
    try:
        (version,) = struct.unpack_from("<I", raw, 4)
    except struct.error:
        raise BundleError(f"{name}: truncated header") from None
    if version != GFL_VERSION:
        raise BundleError(
            f"{name}: unsupported frame-log version {version} "
            f"(want {GFL_VERSION})"
        )
    records: List[FrameRecord] = []
    pos = 8
    while pos < len(raw):
        try:
            length, crc = struct.unpack_from("<II", raw, pos)
        except struct.error:
            raise BundleError(f"{name}: truncated record header") from None
        pos += 8
        payload = raw[pos:pos + length]
        if len(payload) != length:
            raise BundleError(f"{name}: truncated record payload")
        if zlib.crc32(payload) != crc:
            raise BundleError(f"{name}: record CRC mismatch")
        pos += length
        try:
            wall_ns, mono_ns, d, kind, peer_len, frame_len = \
                _REC_HEAD.unpack_from(payload, 0)
        except struct.error:
            raise BundleError(f"{name}: malformed record") from None
        body = payload[_REC_HEAD.size:]
        if len(body) != peer_len + frame_len:
            raise BundleError(f"{name}: record length mismatch")
        peer = body[:peer_len].decode("utf-8", errors="replace")
        frame = body[peer_len:]
        records.append(
            (wall_ns, mono_ns, "in" if d == 0 else "out", peer, kind,
             frame)
        )
    return records


# ---------------------------------------------------------------------
# The per-wire capture ring
# ---------------------------------------------------------------------
class _WireRing:
    """Byte-budgeted frame ring: append evicts oldest until under
    budget.  A small lock per record — the tap sites already sit next
    to an HTTP round trip or a device dispatch, and the bench gate
    bounds the total (blackbox_overhead_ratio >= 0.95)."""

    __slots__ = ("budget", "frames", "nbytes", "frames_total",
                 "bytes_total", "_mu")

    def __init__(self, budget: int):
        self.budget = max(int(budget), 1)
        self.frames: List[FrameRecord] = []
        self.nbytes = 0
        self.frames_total = 0  # monotonic, for the metrics counter
        self.bytes_total = 0
        self._mu = threading.Lock()

    def record(self, rec: FrameRecord) -> None:
        nb = len(rec[5]) + len(rec[3]) + 32
        with self._mu:
            self.frames.append(rec)
            self.nbytes += nb
            self.frames_total += 1
            self.bytes_total += nb
            while self.nbytes > self.budget and len(self.frames) > 1:
                old = self.frames.pop(0)
                self.nbytes -= len(old[5]) + len(old[3]) + 32
            if self.nbytes > self.budget:
                # A single frame larger than the whole budget still
                # captures (the incident frame is the point).
                pass

    def freeze(self) -> List[FrameRecord]:
        with self._mu:
            return list(self.frames)

    def stats(self) -> Tuple[int, int, int]:
        with self._mu:
            return len(self.frames), self.nbytes, self.frames_total


def _frames_from_taken(tb) -> List[bytes]:
    """Reconstruct the original kind-5 ingress frames a native take
    batch (gateway.NativeIngressPump) coalesced: the C++ edge parsed
    and freed the original bytes, but the batch keeps every column plus
    per-frame lane counts, so the frames re-encode byte-identically to
    wire.encode_ingress_frame's layout (no trace trailer — the fast
    lane never carries sampled frames).  Must run BEFORE complete():
    the batch's views die inside it."""
    from . import wire as wire_mod

    nf = int(tb.n_frames)
    if nf <= 0:
        return []
    lanes = np.asarray(tb.frame_lanes, dtype=np.int64)
    bounds = np.zeros(nf + 1, dtype=np.int64)
    np.cumsum(lanes, out=bounds[1:])
    no = np.asarray(tb._no, dtype=np.int64)
    uo = np.asarray(tb._uo, dtype=np.int64)
    frames: List[bytes] = []
    for fi in range(nf):
        lo, hi = int(bounds[fi]), int(bounds[fi + 1])
        n = hi - lo
        n_off = (no[lo:hi + 1] - no[lo]).astype(np.uint32)
        n_blob = bytes(tb._nb[no[lo]:no[hi]])
        u_off = (uo[lo:hi + 1] - uo[lo]).astype(np.uint32)
        u_blob = bytes(tb._ub[uo[lo]:uo[hi]])
        frames.append(b"".join((
            _GUBC_MAGIC,
            struct.pack("<BBI", wire_mod.FRAME_VERSION,
                        wire_mod._FRAME_KIND_INGRESS_REQ, n),
            struct.pack("<I", len(n_blob)), n_off.tobytes(), n_blob,
            struct.pack("<I", len(u_blob)), u_off.tobytes(), u_blob,
            np.ascontiguousarray(tb.algorithm[lo:hi], np.int32).tobytes(),
            np.ascontiguousarray(tb.behavior[lo:hi], np.int32).tobytes(),
            np.ascontiguousarray(tb.hits[lo:hi], np.int64).tobytes(),
            np.ascontiguousarray(tb.limit[lo:hi], np.int64).tobytes(),
            np.ascontiguousarray(tb.duration[lo:hi], np.int64).tobytes(),
        )))
    return frames


# ---------------------------------------------------------------------
# The black box
# ---------------------------------------------------------------------
class BlackBox:
    """One per V1Service (the per-instance keying of the flight-
    recorder fix): the five wire rings, the trigger/coalesce/rate-limit
    state, and the off-thread bundle writer.  `service` may be None for
    ring-only unit use (no bundles)."""

    #: Storm-gather window: triggers arriving within this of the first
    #: one land in the SAME bundle as extra trigger records.
    COALESCE_S = 0.25
    #: Minimum spacing between bundles (manual triggers bypass).
    MIN_INTERVAL_S = 30.0
    #: Safety cap on queued trigger records between bundle writes.
    MAX_PENDING = 1000

    def __init__(self, service=None, path: str = "", budget_mb: int = 64,
                 retain: int = 8, enabled: bool = True):
        self.service = service
        self.path = path or ""
        self.retain = max(int(retain), 1)
        self.budget_bytes = max(int(budget_mb), 1) * (1 << 20)
        self._on = bool(enabled)
        per = max(self.budget_bytes // len(WIRES), 4096)
        self.rings: Dict[str, _WireRing] = {w: _WireRing(per) for w in WIRES}
        self.coalesce_s = self.COALESCE_S
        self.min_interval_s = self.MIN_INTERVAL_S
        self._pending: List[dict] = []
        self._suppressed = 0
        self._force = False
        self._trigger_mu = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_bundle_mono: Optional[float] = None
        self._last_trigger_mono: Optional[float] = None
        self.bundles_written = 0
        self._seq = itertools.count(1)
        self._write_mu = threading.Lock()

    # -- taps ----------------------------------------------------------
    def live(self) -> bool:
        """True when taps would record.  For callers whose capture has
        a pre-tap cost (the gRPC transport re-encodes proto columns as
        a canonical GUBC frame) — everyone else just calls tap()."""
        return not _FORCE_DISABLED and self._on and _ENABLED

    def tap(self, direction: str, peer: str, data) -> None:
        """Record one wire frame.  Tolerates non-frame bodies (JSON,
        empty) by sniffing the GUBC magic — callers pass every POST
        body / response without pre-classifying."""
        if _FORCE_DISABLED or not (self._on and _ENABLED):
            return
        if data is None or len(data) < 10 or data[:4] != _GUBC_MAGIC:
            return
        wire_name = _KIND_WIRE.get(data[5])
        if wire_name is None:
            return
        self.rings[wire_name].record(
            (time.time_ns(), time.monotonic_ns(), direction, peer,
             data[5], bytes(data))
        )

    def tap_taken(self, tb) -> None:
        """Native-edge tap: reconstruct and record the kind-5 frames a
        NativeIngressPump take batch coalesced.  Fenced — diagnostics
        must never fail the pump."""
        if _FORCE_DISABLED or not (self._on and _ENABLED):
            return
        try:
            frames = _frames_from_taken(tb)
        except Exception:  # noqa: BLE001
            logger.exception("blackbox native tap failed")
            return
        ring = self.rings["public"]
        wall, mono = time.time_ns(), time.monotonic_ns()
        for frame in frames:
            ring.record((wall, mono, "in", "", 5, frame))

    # -- triggers ------------------------------------------------------
    def on_trigger(self, kind: str, fields: dict) -> None:
        """tracing.Recorder dump hook: queue one trigger record and
        wake the writer.  Never blocks, never raises into the path
        that fired the event."""
        if _FORCE_DISABLED or not (self._on and _ENABLED):
            return
        rec = {
            "kind": kind,
            "wallNs": time.time_ns(),
            "monoNs": time.monotonic_ns(),
            "fields": {
                k: v for k, v in (fields or {}).items()
                if k not in ("kind", "ts_ns")
            },
        }
        with self._trigger_mu:
            self._last_trigger_mono = time.monotonic()
            if len(self._pending) < self.MAX_PENDING:
                self._pending.append(rec)
            else:
                self._suppressed += 1
            self._ensure_thread()
        self._wake.set()

    def trigger_manual(self, reason: str = "") -> dict:
        """POST /debug/incident: operator-requested bundle — queued
        like any trigger but exempt from the rate limit (an operator
        asking for evidence gets it)."""
        with self._trigger_mu:
            self._force = True
        self.on_trigger("manual", {"reason": reason or "operator"})
        return {"accepted": True, "dir": self.path}

    def _ensure_thread(self) -> None:
        # _trigger_mu held.
        if self._thread is None or not self._thread.is_alive():
            if self._stop.is_set():
                return
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="blackbox-writer"
            )
            self._thread.start()

    def _run(self) -> None:
        while True:
            self._wake.wait()
            if self._stop.is_set():
                return
            self._wake.clear()
            # Coalescing gather window: a breaker storm's triggers all
            # land before this expires and share one bundle.
            if self._stop.wait(self.coalesce_s):
                return
            with self._trigger_mu:
                triggers, self._pending = self._pending, []
                force, self._force = self._force, False
                suppressed, self._suppressed = self._suppressed, 0
            if not triggers:
                continue
            now = time.monotonic()
            if (not force and self._last_bundle_mono is not None
                    and now - self._last_bundle_mono < self.min_interval_s):
                with self._trigger_mu:
                    self._suppressed += len(triggers)
                continue
            if not self.path:
                # Rings always run; bundles need a configured dir.
                continue
            self._last_bundle_mono = now
            try:
                self.write_bundle(triggers, suppressed=suppressed)
            except Exception:  # noqa: BLE001
                logger.exception("blackbox bundle write failed")

    # -- bundle write --------------------------------------------------
    def write_bundle(self, triggers: List[dict],
                     suppressed: int = 0) -> str:
        """Freeze the rings and write one crash-safe bundle directory:
        every file fsynced inside a `.tmp-*` dir, manifest (with the
        per-file CRC table) last, then one atomic rename + dir fsync —
        the snapshot.py write discipline, so a reader never sees a
        partial bundle and a crash leaves only a `.tmp-*` to sweep."""
        name = (
            f"incident-{time.strftime('%Y%m%dT%H%M%S', time.gmtime())}"
            f"-{os.getpid()}-{next(self._seq):04d}"
        )
        with self._write_mu:
            frames = {w: self.rings[w].freeze() for w in WIRES}
            files: Dict[str, bytes] = {}
            rings_meta: Dict[str, dict] = {}
            for w in WIRES:
                blob = encode_frame_log(frames[w])
                files[f"wire-{w}.gfl"] = blob
                rings_meta[w] = {
                    "frames": len(frames[w]),
                    "bytes": sum(len(r[5]) for r in frames[w]),
                    "fingerprint": zlib.crc32(
                        b"".join(r[5] for r in frames[w])
                    ),
                }
            for fname, doc in self._service_docs().items():
                files[fname] = doc
            manifest = {
                "format": BUNDLE_FORMAT,
                "version": BUNDLE_VERSION,
                "name": name,
                "wallNs": time.time_ns(),
                "monoNs": time.monotonic_ns(),
                "gubernatorVersion": _pkg_version(),
                "service": self._service_identity(),
                "triggers": triggers,
                "suppressedTriggers": suppressed,
                "knobs": self._knobs(),
                "faultSeed": self._fault_seed(),
                "rings": rings_meta,
                "files": {
                    fname: {"bytes": len(blob),
                            "crc32": zlib.crc32(blob)}
                    for fname, blob in files.items()
                },
            }
            os.makedirs(self.path, exist_ok=True)
            tmp = os.path.join(self.path, f".tmp-{name}")
            final = os.path.join(self.path, name)
            try:
                os.makedirs(tmp, exist_ok=True)
                for fname, blob in files.items():
                    _write_fsync(os.path.join(tmp, fname), blob)
                _write_fsync(
                    os.path.join(tmp, MANIFEST_NAME),
                    json.dumps(manifest, indent=1, default=str)
                    .encode("utf-8"),
                )
                os.replace(tmp, final)
                _fsync_dir(self.path)
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            self.bundles_written += 1
            logger.warning(
                "blackbox bundle written dir=%s triggers=%s", final,
                [t["kind"] for t in triggers],
            )
            self._prune()
            return final

    def _service_docs(self) -> Dict[str, bytes]:
        """The debug-surface snapshots, each independently fenced — a
        failing section costs that file, never the bundle."""
        svc = self.service
        docs: Dict[str, bytes] = {}
        if svc is None:
            return docs
        from . import saturation, tracing

        def _put(fname, fn):
            try:
                docs[fname] = json.dumps(fn(), default=str).encode("utf-8")
            except Exception:  # noqa: BLE001
                logger.exception("blackbox %s snapshot failed", fname)

        recs = [r for r in (getattr(svc, "recorder", None),
                            tracing.default_recorder()) if r is not None]
        _put("spans.json", lambda: tracing.spans_snapshot(recorders=recs))
        _put("events.json", lambda: tracing.events_snapshot(recorders=recs))
        _put("status.json", svc.debug_status)
        _put("latency.json", lambda: {
            "phases": saturation.phase_snapshot(),
            "express": saturation.express_snapshot(),
            "slo": svc.slo.snapshot(),
        })
        _put("audit.json", svc.auditor.snapshot)
        _put("tenants.json", svc.tenants.snapshot)
        try:
            # The gateway /metrics collect-on-scrape discipline: refresh
            # the scrape-time families under the scrape lock, render.
            m = svc.metrics
            with m.scrape_lock:
                m.observe_cache(svc.store)
                m.observe_dispatch(svc.store)
                m.observe_saturation(svc)
                m.observe_telemetry()
                m.observe_audit(svc)
                m.observe_cost(svc)
                m.observe_native_ingress(svc)
                m.observe_blackbox(svc)
                docs["metrics.prom"] = m.render()
        except Exception:  # noqa: BLE001
            logger.exception("blackbox metrics scrape failed")
        try:
            snap_path = getattr(svc.conf, "snapshot_path", "")
            if snap_path and os.path.exists(snap_path):
                with open(snap_path, "rb") as f:
                    docs["state.snap"] = f.read()
        except Exception:  # noqa: BLE001
            logger.exception("blackbox state-snapshot copy failed")
        return docs

    def _service_identity(self) -> dict:
        svc = self.service
        if svc is None:
            return {}
        rec = getattr(svc, "recorder", None)
        return {
            "advertiseAddress": getattr(svc.conf, "advertise_address", ""),
            "dataCenter": getattr(svc.conf, "data_center", ""),
            "recorder": getattr(rec, "name", ""),
            "pid": os.getpid(),
        }

    def _knobs(self) -> dict:
        svc = self.service
        if svc is None:
            return {}
        import dataclasses

        try:
            b = dataclasses.asdict(svc.conf.behaviors)
        except Exception:  # noqa: BLE001
            return {}
        return {
            k: v for k, v in b.items()
            if isinstance(v, (bool, int, float, str))
        }

    def _fault_seed(self):
        from . import faults as faults_mod

        plan = None
        if self.service is not None:
            plan = getattr(self.service.conf, "fault_plan", None)
        if plan is None:
            plan = faults_mod.active()
        return getattr(plan, "seed", None)

    def _prune(self) -> None:
        try:
            keep = list_bundles(self.path)
            for name in keep[:-self.retain]:
                shutil.rmtree(
                    os.path.join(self.path, name), ignore_errors=True
                )
            # Sweep crash leftovers: a `.tmp-*` older than a minute is
            # a dead writer's partial bundle.
            for entry in os.listdir(self.path):
                if entry.startswith(".tmp-"):
                    p = os.path.join(self.path, entry)
                    if time.time() - os.path.getmtime(p) > 60:
                        shutil.rmtree(p, ignore_errors=True)
        except OSError:
            pass

    # -- status / lifecycle -------------------------------------------
    def snapshot(self) -> dict:
        """The `blackbox` section of GET /debug/status (fed to
        scripts/cluster_status.py's blackbox column)."""
        ring_frames, ring_bytes = {}, {}
        for w, ring in self.rings.items():
            n, nb, _total = ring.stats()
            ring_frames[w] = n
            ring_bytes[w] = nb
        on_disk = len(list_bundles(self.path)) if self.path else 0
        age = None
        if self._last_trigger_mono is not None:
            age = round(time.monotonic() - self._last_trigger_mono, 1)
        return {
            "enabled": bool(self._on and _ENABLED and not _FORCE_DISABLED),
            "dir": self.path,
            "bundles": self.bundles_written,
            "bundlesOnDisk": on_disk,
            "lastTriggerAgeS": age,
            "ringFrames": ring_frames,
            "ringBytes": ring_bytes,
            "ringBudgetBytes": self.budget_bytes,
            "suppressedTriggers": self._suppressed,
        }

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)


# ---------------------------------------------------------------------
# Bundle loading (shared by replay + fsck)
# ---------------------------------------------------------------------
class Bundle:
    """A fully-verified on-disk incident bundle."""

    def __init__(self, path: str, manifest: dict,
                 frames: Dict[str, List[FrameRecord]]):
        self.path = path
        self.manifest = manifest
        self.frames = frames

    def doc(self, name: str):
        """Parse one of the bundle's JSON documents (status.json,
        audit.json, ...); None when the bundle omitted it."""
        p = os.path.join(self.path, name)
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return json.loads(f.read())

    def merged_records(self) -> List[FrameRecord]:
        """Every captured frame across all wires in capture (monotonic
        stamp) order — the replay drive order."""
        out: List[FrameRecord] = []
        for recs in self.frames.values():
            out.extend(recs)
        out.sort(key=lambda r: r[1])
        return out


def list_bundles(path: str) -> List[str]:
    try:
        return sorted(
            e for e in os.listdir(path)
            if e.startswith("incident-")
            and os.path.isdir(os.path.join(path, e))
        )
    except OSError:
        return []


def load_bundle(path: str) -> Bundle:
    """Open + verify one bundle directory; BundleError on ANY defect —
    missing/corrupt manifest, wrong format/version, per-file size or
    CRC mismatch, malformed frame log.  Verification is total before
    any frame is surfaced (the no-half-replay contract)."""
    mp = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mp, "rb") as f:
            manifest = json.loads(f.read())
    except OSError as e:
        raise BundleError(f"manifest unreadable: {e}") from e
    except ValueError as e:
        raise BundleError(f"manifest corrupt: {e}") from e
    if not isinstance(manifest, dict):
        raise BundleError("manifest corrupt: not an object")
    if manifest.get("format") != BUNDLE_FORMAT:
        raise BundleError(
            f"not a blackbox bundle (format={manifest.get('format')!r})"
        )
    if manifest.get("version") != BUNDLE_VERSION:
        raise BundleError(
            f"unsupported bundle version {manifest.get('version')!r} "
            f"(want {BUNDLE_VERSION})"
        )
    table = manifest.get("files")
    if not isinstance(table, dict):
        raise BundleError("manifest corrupt: missing files table")
    blobs: Dict[str, bytes] = {}
    for fname, meta in table.items():
        fp = os.path.join(path, fname)
        try:
            with open(fp, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise BundleError(f"{fname}: unreadable: {e}") from e
        if len(blob) != meta.get("bytes"):
            raise BundleError(
                f"{fname}: size mismatch (have {len(blob)}, manifest "
                f"says {meta.get('bytes')}) — truncated or tampered"
            )
        if zlib.crc32(blob) != meta.get("crc32"):
            raise BundleError(f"{fname}: CRC mismatch — corrupt")
        blobs[fname] = blob
    frames: Dict[str, List[FrameRecord]] = {}
    for w in WIRES:
        fname = f"wire-{w}.gfl"
        if fname in blobs:
            frames[w] = decode_frame_log(blobs[fname], name=fname)
        else:
            frames[w] = []
    return Bundle(path, manifest, frames)


# ---------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------
def _write_fsync(path: str, blob: bytes) -> None:
    with open(path, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _pkg_version() -> str:
    try:
        from . import __version__

        return __version__
    except Exception:  # noqa: BLE001
        return "unknown"
