// Native host runtime: key -> device-slot table + batch round planner.
//
// This is the C++ twin of models/slot_table.py (the reference's LRU
// cache role, cache.go:52-218) plus the round-planning loop of
// models/shard.py::RoundPlanner. The TPU kernel wants whole batches of
// unique (key, slot) lanes; the host must resolve string keys to dense
// slots, keep LRU order for eviction, mirror expiry (expiry == miss,
// cache.go:138-163), and split duplicate-key batches into sequential
// rounds (the vectorized equivalent of the reference's mutex
// serialization, gubernator.go:336-337). All of that is pure pointer
// chasing that Python does 50-100x slower than C++ — this module exists
// so the device kernel, not the host, is the bottleneck.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
// Thread-safety contract: each Table carries its own recursive mutex,
// taken by every extern-C entry that touches it.  This is what lets the
// overlapped dispatch pipeline run batch N+1's PLANNING concurrently
// with batch N's in-flight DECODE/COMMIT (models/shard.py
// ColumnarPipeline): the two stages hold different Python locks, and
// ctypes releases the GIL for the call's duration, so without internal
// locking they would race on the same hash map.  Interleaving at call
// granularity is safe by the same argument as pipelined planning
// itself — a plan that runs before an older batch's commit observes
// expiry lagging by the unresolved depth (revalidated device-side),
// and pending_write refcounts keep in-flight slots uneviction-able.
// Cross-batch ORDERING is the Python tier's job (plan-order tickets +
// the FIFO drain); this mutex only makes each call atomic.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

// FNV-1 / FNV-1a 64: the shard-routing hash (replicated_hash.go:31).
// Single definitions shared by gt_fnv1_batch and the mesh planner so
// shard routing cannot diverge between the two.
inline uint64_t fnv1a64(const char* p, const char* end) {
  uint64_t h = 14695981039346656037ull;
  for (; p < end; ++p) {
    h ^= (uint64_t)(unsigned char)*p;
    h *= 1099511628211ull;
  }
  return h;
}

inline uint64_t fnv1_64(const char* p, const char* end) {
  uint64_t h = 14695981039346656037ull;
  for (; p < end; ++p) {
    h *= 1099511628211ull;
    h ^= (uint64_t)(unsigned char)*p;
  }
  return h;
}

struct Table {
  // Guards every member below against concurrent extern-C calls
  // (recursive: gt_mesh_* entries call gt_batch_* entries on the same
  // table).  See the thread-safety contract at the top of the file.
  std::recursive_mutex mu;
  int64_t capacity;
  // slot -> key (empty string + mapped=false when free)
  std::vector<std::string> slot_key;
  std::vector<uint8_t> slot_mapped;
  std::vector<int64_t> expire_ms;
  // In-flight (planned, not yet committed) device writes per slot.
  // While >0 the device row is fresher than expire_ms, so liveness is
  // device-authoritative — the pipelined twin of the planner's chained
  // lanes (see gt_batch_plan).  Nonzero only between a columnar batch's
  // plan and its commit.
  std::vector<int32_t> pending_write;
  // LRU intrusive list over slots; head = least recent. -1 = null.
  std::vector<int32_t> lru_prev, lru_next;
  int32_t lru_head = -1, lru_tail = -1;
  std::vector<int32_t> free_slots;  // stack, top = back
  std::unordered_map<std::string, int32_t> key_to_slot;
  int64_t hits = 0, misses = 0, evictions = 0;
  // Bumped on every key->front-slot MAPPING change (assign, remap,
  // evict, remove).  NOT bumped by in-place expiry reuse (same key,
  // same slot) or value/expire writes.  Lets the GLOBAL sync skip
  // owner-slot re-verification for shards whose mapping is provably
  // unchanged since the last sync (O(active-gslots) -> O(changed)).
  uint64_t map_generation = 0;

  // ---- two-tier mode (back_capacity > 0) ----------------------------
  // The device keeps a small FRONT table (every kernel lane addresses
  // it — random-row scatter cost scales with table size, measured
  // ~2.4ns/slot on TPU v5e) plus a big BACK table written only by
  // batched demotion scatters.  Front LRU eviction DEMOTES the row
  // (device move, state preserved) instead of dropping it; a later
  // lookup PROMOTES it back (cheap device gather).  The host tracks
  // key locations and queues the device moves; dispatchers drain them
  // (gt_table_take_moves -> ops/buckets.apply_moves) before any
  // program that reads front rows.  The back tier evicts FIFO (ring
  // cursor) — only then is bucket state truly lost, matching the
  // reference's plain LRU loss semantics at total capacity.
  int64_t back_capacity = 0;
  std::unordered_map<std::string, int32_t> key_to_back;
  std::vector<std::string> back_key;  // back slot -> key
  std::vector<uint8_t> back_mapped;
  std::vector<int64_t> back_expire;
  int64_t back_clock = 0;  // FIFO allocation cursor
  int64_t back_size = 0, back_evictions = 0, demotions = 0, promotions = 0;
  // Pending device moves.  promo kind: 0 = gather from back slot, 1 =
  // gather from FRONT slot (a key demoted and re-promoted inside one
  // drain window — its row never reached the back table, so the
  // device copies front->front; the demo record still parks the stale
  // copy in the back slot, which the host no longer maps).
  std::vector<int32_t> mv_promo_kind, mv_promo_src, mv_promo_dst;
  std::vector<int32_t> mv_demo_src, mv_demo_dst;
  // back slot -> index into mv_demo (this window) for cycle rewrite
  std::unordered_map<int32_t, int32_t> pending_demo_by_back;
  // per front slot: index into mv_promo_* of a queued-but-undrained
  // promotion (-1 none).  The row is not on device yet, so eviction
  // must prefer other slots and, if forced, CANCEL the record.
  std::vector<int32_t> pending_promo;

  explicit Table(int64_t cap)
      : capacity(cap),
        slot_key(cap),
        slot_mapped(cap, 0),
        expire_ms(cap, 0),
        pending_write(cap, 0),
        lru_prev(cap, -1),
        lru_next(cap, -1),
        pending_promo(cap, -1) {
    free_slots.reserve(cap);
    for (int64_t i = cap - 1; i >= 0; --i) free_slots.push_back((int32_t)i);
    key_to_slot.reserve((size_t)cap * 2);
  }

  void lru_unlink(int32_t s) {
    int32_t p = lru_prev[s], n = lru_next[s];
    if (p >= 0) lru_next[p] = n; else if (lru_head == s) lru_head = n;
    if (n >= 0) lru_prev[n] = p; else if (lru_tail == s) lru_tail = p;
    lru_prev[s] = lru_next[s] = -1;
  }

  void lru_push_back(int32_t s) {  // most recently used
    lru_prev[s] = lru_tail;
    lru_next[s] = -1;
    if (lru_tail >= 0) lru_next[lru_tail] = s;
    lru_tail = s;
    if (lru_head < 0) lru_head = s;
  }

  void touch(int32_t s) {
    if (lru_tail == s) return;
    lru_unlink(s);
    lru_push_back(s);
  }

  void unmap_slot(int32_t s) {
    if (!slot_mapped[s]) return;
    key_to_slot.erase(slot_key[s]);
    slot_key[s].clear();
    slot_mapped[s] = 0;
    expire_ms[s] = 0;
    lru_unlink(s);
    free_slots.push_back(s);
    ++map_generation;
  }

  void enable_back(int64_t cap) {
    back_capacity = cap;
    back_key.resize(cap);
    back_mapped.assign(cap, 0);
    back_expire.assign(cap, 0);
    key_to_back.reserve((size_t)cap * 2);
  }

  void unmap_back(int32_t b) {
    if (!back_mapped[b]) return;
    key_to_back.erase(back_key[b]);
    back_key[b].clear();
    back_mapped[b] = 0;
    back_expire[b] = 0;
    --back_size;
  }

  // Neutralize a queued demo targeting back slot b (src=-1 device
  // no-op): required whenever b is freed or reused mid-window, or the
  // move program could scatter two rows onto one destination.
  void cancel_pending_demo(int32_t b) {
    auto pd = pending_demo_by_back.find(b);
    if (pd != pending_demo_by_back.end()) {
      mv_demo_src[(size_t)pd->second] = -1;
      pending_demo_by_back.erase(pd);
    }
  }

  // A back slot mid-promotion: lookup_or_assign resolves the promo
  // source BEFORE allocating the front slot, and that allocation's
  // eviction can demote another key — alloc_back must not wrap the
  // FIFO cursor onto the in-flight source, or the promoted key would
  // adopt the victim's row (found by round-4 review, repro'd with
  // front=1/back=1).
  int32_t promo_in_flight = -1;

  // FIFO ring allocation; wrapping onto a live entry drops it (the
  // two-tier design's only true state loss).  Returns -1 when no slot
  // is usable (back_capacity==1 and that slot is mid-promotion): the
  // caller drops the row instead of demoting.
  int32_t alloc_back(const std::string& key) {
    int32_t b = (int32_t)(back_clock % back_capacity);
    ++back_clock;
    if (b == promo_in_flight) {
      if (back_capacity == 1) return -1;
      b = (int32_t)(back_clock % back_capacity);
      ++back_clock;
    }
    if (back_mapped[b]) {
      unmap_back(b);
      ++back_evictions;
      ++evictions;
    }
    cancel_pending_demo(b);
    back_key[b] = key;
    back_mapped[b] = 1;
    key_to_back.emplace(key, b);
    ++back_size;
    return b;
  }

  // Demote the (still-live) key occupying front slot s: queue the
  // device row move front[s] -> back[b] and move the host mapping.
  // Expired occupants are simply dropped — dead state is not worth a
  // back slot.
  void evict_front(int32_t s, int64_t now_ms) {
    lru_unlink(s);
    const std::string k = std::move(slot_key[s]);
    key_to_slot.erase(k);
    slot_mapped[s] = 0;
    // Demotion preserves state ONLY when the device row at s really is
    // this key's current state.  Under the all-pending starvation
    // fallback the chosen slot may have (a) a queued promotion whose
    // row hasn't arrived — demoting would park the PREVIOUS occupant's
    // row under this key's name (cross-key corruption, round-4 review
    // repro) — cancel the promo and drop instead; (b) an in-flight
    // batch write (pending_write) — the row is mid-air, drop.  Both
    // degrade to the documented reference-grade loss, never to serving
    // another key's counters.
    if (pending_promo[s] >= 0) {
      mv_promo_src[(size_t)pending_promo[s]] = -1;  // device no-op
      pending_promo[s] = -1;
      ++back_evictions;  // the promoted state is lost
    } else if (back_capacity > 0 && pending_write[s] == 0 &&
               expire_ms[s] >= now_ms) {
      int32_t b = alloc_back(k);
      if (b >= 0) {
        back_expire[b] = expire_ms[s];
        pending_demo_by_back[b] = (int32_t)mv_demo_src.size();
        mv_demo_src.push_back(s);
        mv_demo_dst.push_back(b);
        ++demotions;
      } else {
        ++back_evictions;  // degenerate: nowhere to park the row
      }
    }
    expire_ms[s] = 0;
    ++evictions;
    ++map_generation;
  }

  // Re-map an unmapped slot to `key` (the remove-then-recreate chain:
  // an earlier lane freed the slot, a later round recreated the key on
  // device).  Returns false when the key is meanwhile mapped elsewhere.
  // Negative expire is the narrow-wire keep-sentinel; an unmapped slot
  // has no prior value to keep, so it clamps to 0 (already expired).
  bool remap(int32_t s, const char* key, size_t len, int64_t expire) {
    std::string k(key, len);
    if (!key_to_slot.emplace(k, s).second) return false;
    slot_key[s] = std::move(k);
    slot_mapped[s] = 1;
    expire_ms[s] = expire >= 0 ? expire : 0;
    for (size_t j = free_slots.size(); j > 0; --j) {
      if (free_slots[j - 1] == s) {
        free_slots[j - 1] = free_slots.back();
        free_slots.pop_back();
        break;
      }
    }
    lru_push_back(s);
    ++map_generation;
    return true;
  }

  // (slot, exists): exists=false means kernel treats as fresh create.
  // Mirrors slot_table.py::lookup_or_assign, except for pipelining
  // state the Python twin does not model: pending_write liveness and
  // pending-aware eviction only matter between a columnar batch's plan
  // and commit, and the pipelined path requires the native runtime —
  // the Python twin never observes in-flight writes, so the twins agree
  // on every state the Python table can reach.
  std::pair<int32_t, bool> lookup_or_assign(const char* key, size_t len,
                                            int64_t now_ms) {
    std::string k(key, len);
    auto it = key_to_slot.find(k);
    if (it != key_to_slot.end()) {
      int32_t s = it->second;
      touch(s);
      // Strict expiry (cache.go:151); an uncommitted in-flight write
      // makes the device row authoritative regardless of the stale
      // host expire (pipelined batches — the kernel revalidates).
      if (expire_ms[s] >= now_ms || pending_write[s] > 0) {
        ++hits;
        return {s, true};
      }
      ++misses;  // expired: recycle same slot in place
      return {s, false};
    }
    // Two-tier: a live row demoted to the back tier promotes (a
    // logical cache hit — the state survives the round trip).
    int32_t promo_b = -1;
    if (back_capacity > 0) {
      auto itb = key_to_back.find(k);
      if (itb != key_to_back.end()) {
        int32_t b = itb->second;
        if (back_expire[b] >= now_ms) {
          promo_b = b;
        } else {
          cancel_pending_demo(b);
          unmap_back(b);  // expired in back: plain miss-create
        }
      }
    }
    if (promo_b >= 0) ++hits; else ++misses;
    promo_in_flight = promo_b;  // shield the source from FIFO reuse
    int32_t s;
    if (!free_slots.empty()) {
      s = free_slots.back();
      free_slots.pop_back();
    } else {
      // Evict LRU (cache.go:115-130), skipping slots whose device write
      // from an earlier pipelined batch is still in flight — stealing
      // one drops that batch's device state mid-air and invalidates its
      // plan-time chaining assumptions — and slots awaiting a queued
      // promotion this drain window (their device row lands with the
      // NEXT move program; demoting one would copy a pre-promotion
      // row).  Walk from the cold end; under pipelining the pending
      // slots are the recently-touched ones, so the head is normally
      // clean.  Fall back to the raw head only when every slot is
      // pending (capacity fully in flight).
      // Preference ladder: fully clean slot > promo-free slot (in-
      // flight write: evict_front drops instead of demoting) > raw
      // head (pending promo: evict_front cancels the record — loss,
      // never corruption).
      s = -1;
      for (int32_t cand = lru_head; cand >= 0; cand = lru_next[cand]) {
        if (pending_write[cand] == 0 && pending_promo[cand] < 0) {
          s = cand;
          break;
        }
      }
      if (s < 0) {
        for (int32_t cand = lru_head; cand >= 0; cand = lru_next[cand]) {
          if (pending_promo[cand] < 0) {
            s = cand;
            break;
          }
        }
      }
      if (s < 0) s = lru_head;
      evict_front(s, now_ms);
    }
    key_to_slot.emplace(std::move(k), s);
    slot_key[s].assign(key, len);
    slot_mapped[s] = 1;
    lru_push_back(s);
    ++map_generation;
    if (promo_b >= 0) {
      expire_ms[s] = back_expire[promo_b];
      // Queue the device move.  A demo still pending for this back
      // slot (same drain window) means the row never left the front
      // table — copy front->front (kind 1) instead of reading the
      // not-yet-written back slot, and cancel the parked demo copy
      // (its destination is now free for same-window reuse).
      auto pd = pending_demo_by_back.find(promo_b);
      if (pd != pending_demo_by_back.end()) {
        mv_promo_kind.push_back(1);
        mv_promo_src.push_back(mv_demo_src[(size_t)pd->second]);
        mv_demo_src[(size_t)pd->second] = -1;
        pending_demo_by_back.erase(pd);
      } else {
        mv_promo_kind.push_back(0);
        mv_promo_src.push_back(promo_b);
      }
      mv_promo_dst.push_back(s);
      pending_promo[s] = (int32_t)mv_promo_dst.size() - 1;
      unmap_back(promo_b);
      promo_in_flight = -1;
      ++promotions;
      return {s, true};
    }
    promo_in_flight = -1;
    expire_ms[s] = 0;
    return {s, false};
  }
};

struct Batch {
  Table* table;
  const char* keys;        // concatenated key bytes (borrowed)
  const int64_t* offsets;  // n+1 offsets into keys (borrowed)
  int64_t n;
  int64_t now_ms;
  // Lanes not yet scheduled, in request order (per-key order is what
  // matters; cross-key order is free, as in the reference's goroutine
  // fan-out).
  std::vector<int32_t> pending;
  // per-lane resolution cache (a deferred lane keeps its captured slot)
  std::vector<int32_t> slot;
  std::vector<uint8_t> exists, resolved;
  bool committed = false;
  // last emitted round
  std::vector<int32_t> round_lane;
  // full-plan mode (gt_batch_plan): lanes in emission order across all
  // rounds, consumed by gt_batch_commit_plan
  std::vector<int32_t> plan_order;

  Batch(Table* t, const char* k, const int64_t* off, int64_t n_, int64_t now)
      : table(t), keys(k), offsets(off), n(n_), now_ms(now),
        slot(n_, -1), exists(n_, 0), resolved(n_, 0) {
    pending.reserve(n_);
    for (int64_t i = 0; i < n_; ++i) pending.push_back((int32_t)i);
  }

  const char* key_ptr(int64_t i) const { return keys + offsets[i]; }
  size_t key_len(int64_t i) const { return (size_t)(offsets[i + 1] - offsets[i]); }
};

// Per-table lock for the extern-C surface (see the thread-safety
// contract at the top of the file).
#define GT_LOCK(tp) std::lock_guard<std::recursive_mutex> _gt_guard((tp)->mu)

}  // namespace

extern "C" {

void* gt_table_new(int64_t capacity) { return new Table(capacity); }
void gt_table_free(void* t) { delete (Table*)t; }
int64_t gt_table_len(void* t) {
  GT_LOCK((Table*)t);
  return (int64_t)((Table*)t)->key_to_slot.size();
}

void gt_table_stats(void* tv, int64_t* out) {  // hits, misses, evictions
  Table* t = (Table*)tv;
  GT_LOCK(t);
  out[0] = t->hits; out[1] = t->misses; out[2] = t->evictions;
}

// Single-counter read: plan_grouped_python polls this around every
// lookup to detect evictions, so it must not marshal the whole stats
// array per call.
int64_t gt_table_evictions(void* tv) {
  GT_LOCK((Table*)tv);
  return ((Table*)tv)->evictions;
}

// Mapping-change generation (see Table::map_generation): equal reads
// across two points in time guarantee no key->front-slot mapping
// changed between them.
uint64_t gt_table_generation(void* tv) {
  GT_LOCK((Table*)tv);
  return ((Table*)tv)->map_generation;
}

int32_t gt_table_get_slot(void* tv, const char* key, int64_t len) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  auto it = t->key_to_slot.find(std::string(key, (size_t)len));
  return it == t->key_to_slot.end() ? -1 : it->second;
}

// Single-key resolve (Store-SPI path drives lookups one at a time).
void gt_table_lookup_or_assign(void* tv, const char* key, int64_t len,
                               int64_t now_ms, int32_t* out_slot,
                               uint8_t* out_exists) {
  GT_LOCK((Table*)tv);
  auto [s, e] = ((Table*)tv)->lookup_or_assign(key, (size_t)len, now_ms);
  *out_slot = s;
  *out_exists = e ? 1 : 0;
}

void gt_table_remove(void* tv, const char* key, int64_t len) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  std::string k(key, (size_t)len);
  auto it = t->key_to_slot.find(k);
  if (it != t->key_to_slot.end()) t->unmap_slot(it->second);
  if (t->back_capacity > 0) {
    auto itb = t->key_to_back.find(k);
    if (itb != t->key_to_back.end()) {
      t->cancel_pending_demo(itb->second);
      t->unmap_back(itb->second);
    }
  }
}

// ---- two-tier back tier -----------------------------------------------

void gt_table_enable_back(void* tv, int64_t back_capacity) {
  GT_LOCK((Table*)tv);
  ((Table*)tv)->enable_back(back_capacity);
}

// out: total keys (front+back), back keys, demotions, promotions,
// back evictions (true state loss)
void gt_table_tier_stats(void* tv, int64_t* out) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  out[0] = (int64_t)t->key_to_slot.size() + t->back_size;
  out[1] = t->back_size;
  out[2] = t->demotions;
  out[3] = t->promotions;
  out[4] = t->back_evictions;
}

void gt_table_move_counts(void* tv, int64_t* n_promo, int64_t* n_demo) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  *n_promo = (int64_t)t->mv_promo_src.size();
  *n_demo = (int64_t)t->mv_demo_src.size();
}

// Drain the queued device moves into caller arrays (sized from
// gt_table_move_counts) and close the drain window: after this call
// the rows are considered ON DEVICE in their new homes, so the
// dispatcher MUST run the move program (ops/buckets.apply_moves)
// with exactly these records before any other device program.
void gt_table_take_moves(void* tv, int32_t* promo_kind, int32_t* promo_src,
                         int32_t* promo_dst, int32_t* demo_src,
                         int32_t* demo_dst) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  std::memcpy(promo_kind, t->mv_promo_kind.data(),
              t->mv_promo_kind.size() * sizeof(int32_t));
  std::memcpy(promo_src, t->mv_promo_src.data(),
              t->mv_promo_src.size() * sizeof(int32_t));
  std::memcpy(promo_dst, t->mv_promo_dst.data(),
              t->mv_promo_dst.size() * sizeof(int32_t));
  std::memcpy(demo_src, t->mv_demo_src.data(),
              t->mv_demo_src.size() * sizeof(int32_t));
  std::memcpy(demo_dst, t->mv_demo_dst.data(),
              t->mv_demo_dst.size() * sizeof(int32_t));
  for (int32_t s : t->mv_promo_dst) t->pending_promo[s] = -1;
  t->mv_promo_kind.clear();
  t->mv_promo_src.clear();
  t->mv_promo_dst.clear();
  t->mv_demo_src.clear();
  t->mv_demo_dst.clear();
  t->pending_demo_by_back.clear();
}

// Snapshot protocol for the back tier (Loader.Save needs every live
// item): gt_table_back_size for buffer sizing, then gt_table_back_keys
// fills (back_slots, expire, offsets[count+1], key bytes).
void gt_table_back_size(void* tv, int64_t* count, int64_t* total_bytes) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  *count = t->back_size;
  int64_t bytes = 0;
  for (auto& kv : t->key_to_back) bytes += (int64_t)kv.first.size();
  *total_bytes = bytes;
}

void gt_table_back_keys(void* tv, int32_t* slots, int64_t* expire,
                        int64_t* offsets, char* bytes) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  int64_t i = 0, off = 0;
  for (auto& kv : t->key_to_back) {
    slots[i] = kv.second;
    expire[i] = t->back_expire[kv.second];
    offsets[i] = off;
    std::memcpy(bytes + off, kv.first.data(), kv.first.size());
    off += (int64_t)kv.first.size();
    ++i;
  }
  offsets[i] = off;
}

void gt_table_set_expire(void* tv, int32_t slot, int64_t expire) {
  GT_LOCK((Table*)tv);
  ((Table*)tv)->expire_ms[slot] = expire;
}

// Bulk expiry read for the narrow-wire keep-sentinel decode: lanes
// whose expire/reset passed through unchanged reconstruct the absolute
// value from the host table instead of a (clippable) delta.
void gt_table_get_expire(void* tv, const int32_t* slots, int64_t n,
                         int64_t* out) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  for (int64_t i = 0; i < n; ++i)
    out[i] = (slots[i] >= 0 && slots[i] < t->capacity) ? t->expire_ms[slots[i]] : 0;
}

// Fold kernel outputs back (slot_table.py::commit): slots<0 skipped.
void gt_table_commit(void* tv, const int32_t* slots, const int64_t* expire,
                     const uint8_t* removed, int64_t n) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  for (int64_t i = 0; i < n; ++i) {
    int32_t s = slots[i];
    if (s < 0) continue;
    if (removed[i]) t->unmap_slot(s);
    else t->expire_ms[s] = expire[i];
  }
}

// Commit with the staleness guard (slot_table.py::commit keys check): a
// lane whose slot was remapped to a different key after scheduling (LRU
// eviction mid-batch) must not touch the slot's new owner. Used by the
// Python round loop (Store-SPI path); the planner path enforces this
// per-round in gt_batch_commit_round.
void gt_table_commit_keys(void* tv, const int32_t* slots,
                          const int64_t* expire, const uint8_t* removed,
                          const char* keys, const int64_t* offsets,
                          int64_t n) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  for (int64_t i = 0; i < n; ++i) {
    int32_t s = slots[i];
    if (s < 0) continue;
    size_t len = (size_t)(offsets[i + 1] - offsets[i]);
    if (!t->slot_mapped[s]) {
      if (!removed[i]) t->remap(s, keys + offsets[i], len, expire[i]);
      continue;
    }
    if (t->slot_key[s].compare(0, std::string::npos, keys + offsets[i], len) != 0)
      continue;  // slot remapped mid-batch; this lane is stale
    if (removed[i]) t->unmap_slot(s);
    else t->expire_ms[s] = expire[i];
  }
}

// Snapshot protocol: first call gt_table_keys_size for total bytes, then
// gt_table_keys to fill (slots, offsets[count+1], bytes).
void gt_table_keys_size(void* tv, int64_t* count, int64_t* total_bytes) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  *count = (int64_t)t->key_to_slot.size();
  int64_t bytes = 0;
  for (auto& kv : t->key_to_slot) bytes += (int64_t)kv.first.size();
  *total_bytes = bytes;
}

void gt_table_keys(void* tv, int32_t* slots, int64_t* offsets, char* bytes) {
  Table* t = (Table*)tv;
  GT_LOCK(t);
  int64_t i = 0, off = 0;
  for (auto& kv : t->key_to_slot) {
    slots[i] = kv.second;
    offsets[i] = off;
    std::memcpy(bytes + off, kv.first.data(), kv.first.size());
    off += (int64_t)kv.first.size();
    ++i;
  }
  offsets[i] = off;
}

void* gt_batch_begin(void* tv, const char* keys, const int64_t* offsets,
                     int64_t n, int64_t now_ms) {
  return new Batch((Table*)tv, keys, offsets, n, now_ms);
}

// Emit the next round: walk the pending lanes in request order, taking
// every lane whose key AND slot are not yet used this round; duplicates
// stay pending for a later round (skip-and-defer). The k-th request for
// a key still observes the (k-1)-th's committed state — per-key order
// is preserved because the earlier occurrence is always taken first —
// while hot-key batches need only max-multiplicity rounds instead of
// one round per duplicate. Returns lane count m; fills lane_idx
// (original positions), slots, exists.
int64_t gt_batch_next_round(void* bv, int32_t* lane_idx, int32_t* slots,
                            uint8_t* exists) {
  Batch* b = (Batch*)bv;
  Table* t = b->table;
  GT_LOCK(t);
  if (b->pending.empty()) return 0;
  std::unordered_map<std::string, int> seen_keys;
  std::unordered_map<int32_t, int> used_slots;
  seen_keys.reserve(b->pending.size() * 2);
  used_slots.reserve(b->pending.size() * 2);
  b->round_lane.clear();
  std::vector<int32_t> deferred;
  int64_t m = 0;
  for (int32_t i : b->pending) {
    std::string k(b->key_ptr(i), b->key_len(i));
    if (seen_keys.count(k)) {  // duplicate: must see this round's commit
      deferred.push_back(i);
      continue;
    }
    if (!b->resolved[i]) {
      auto [s, e] = t->lookup_or_assign(b->key_ptr(i), b->key_len(i), b->now_ms);
      b->slot[i] = s;
      b->exists[i] = e ? 1 : 0;
      b->resolved[i] = 1;
    }
    if (used_slots.count(b->slot[i])) {  // eviction collision: defer as-is
      deferred.push_back(i);
      seen_keys.emplace(std::move(k), 1);  // later same-key lanes defer too
      continue;
    }
    lane_idx[m] = i;
    slots[m] = b->slot[i];
    exists[m] = b->exists[i];
    b->round_lane.push_back(i);
    seen_keys.emplace(std::move(k), 1);
    used_slots.emplace(b->slot[i], 1);
    ++m;
  }
  b->pending.swap(deferred);
  return m;
}

// Commit kernel outputs for the lanes of the LAST emitted round.
void gt_batch_commit_round(void* bv, const int64_t* new_expire,
                           const uint8_t* removed) {
  Batch* b = (Batch*)bv;
  Table* t = b->table;
  GT_LOCK(t);
  for (size_t j = 0; j < b->round_lane.size(); ++j) {
    int32_t i = b->round_lane[j];
    int32_t s = b->slot[i];
    if (s < 0) continue;
    // Staleness guard (slot_table.py::commit keys check): only commit
    // if the slot still maps this lane's key.
    if (!t->slot_mapped[s] ||
        t->slot_key[s].compare(0, std::string::npos, b->key_ptr(i),
                               b->key_len(i)) != 0)
      continue;
    if (removed[j]) t->unmap_slot(s);
    else t->expire_ms[s] = new_expire[j];
  }
}

// Plan EVERY round upfront — no interleaved device commits — so the
// whole batch runs as ONE device dispatch (ops/buckets.py apply_rounds:
// a lax.while_loop over rounds).  Per lane i fills round_id / slot /
// exists and returns the round count.
//
// Chained lanes (key already emitted in an earlier round of this batch)
// get exists=1: the device row was just written by this very batch, so
// device-side liveness (expire_at >= now) is authoritative — including
// the remove-then-recreate chain, where the earlier round stamped
// expire_at=0.  This removes the need for host expire updates between
// rounds, which is exactly what forces a blocking device->host readback
// per round in the interleaved design.
// Shared round scheduler for both full-plan entry points: walks
// b->pending (in request order) emitting rounds from `round` upward,
// deferring later same-key occurrences and eviction collisions.
// `occ`/`write` may be null (gt_batch_plan); when present each emitted
// lane gets occ=0, write=1 — every round-scheme lane scatters.
//
// key -> slot at first emission: a later lane is chained (device-
// authoritative) only while it still resolves to that same slot; a
// mid-batch eviction reassigning the key to a fresh slot falls back
// to the host's exists (the state was lost, as in the reference's
// LRU eviction of a live item).
static int64_t plan_rounds(Batch* b, int64_t round, int32_t* round_id,
                           int32_t* slots, uint8_t* exists, int32_t* occ,
                           uint8_t* write,
                           std::unordered_map<int32_t, std::string_view>& slot_owner) {
  Table* t = b->table;
  while (!b->pending.empty()) {
    std::unordered_map<std::string_view, int> seen_keys;
    std::unordered_map<int32_t, int> used_slots;
    seen_keys.reserve(b->pending.size() * 2);
    used_slots.reserve(b->pending.size() * 2);
    std::vector<int32_t> deferred;
    for (int32_t i : b->pending) {
      std::string_view k(b->key_ptr(i), b->key_len(i));
      if (seen_keys.count(k)) {
        deferred.push_back(i);
        continue;
      }
      if (!b->resolved[i]) {
        auto [s, e] = t->lookup_or_assign(b->key_ptr(i), b->key_len(i), b->now_ms);
        b->slot[i] = s;
        b->exists[i] = e ? 1 : 0;
        b->resolved[i] = 1;
      }
      // Slot takeover: a DIFFERENT key's create (mid-batch eviction)
      // is already scheduled on this lane's captured slot — running
      // here would corrupt the new owner's device state.  Re-resolve:
      // this key is no longer mapped, so it gets a fresh slot.
      auto so = slot_owner.find(b->slot[i]);
      if (so != slot_owner.end() && so->second != k) {
        auto [s, e] = t->lookup_or_assign(b->key_ptr(i), b->key_len(i), b->now_ms);
        b->slot[i] = s;
        b->exists[i] = e ? 1 : 0;
      }
      if (used_slots.count(b->slot[i])) {  // eviction collision: defer as-is
        deferred.push_back(i);
        seen_keys.emplace(k, 1);
        continue;
      }
      round_id[i] = (int32_t)round;
      slots[i] = b->slot[i];
      if (occ != nullptr) occ[i] = 0;
      if (write != nullptr) write[i] = 1;
      so = slot_owner.find(b->slot[i]);
      exists[i] = (so != slot_owner.end() && so->second == k)
                      ? 1  // chained: device state authoritative
                      : b->exists[i];
      b->plan_order.push_back(i);
      ++t->pending_write[b->slot[i]];
      seen_keys.emplace(k, 1);
      slot_owner[b->slot[i]] = k;
      used_slots.emplace(b->slot[i], 1);
    }
    b->pending.swap(deferred);
    ++round;
  }
  return round;
}

int64_t gt_batch_plan(void* bv, int32_t* round_id, int32_t* slots,
                      uint8_t* exists) {
  Batch* b = (Batch*)bv;
  GT_LOCK(b->table);
  b->plan_order.clear();
  b->plan_order.reserve((size_t)b->n);
  std::unordered_map<int32_t, std::string_view> slot_owner;
  slot_owner.reserve((size_t)b->n * 2);
  return plan_rounds(b, 0, round_id, slots, exists, nullptr, nullptr,
                     slot_owner);
}

// Fold the planned batch's kernel outputs (indexed by ORIGINAL lane)
// back into the table, in emission order so the last write per key
// wins.  Unlike the per-round staleness guard, an unmapped slot is
// re-mapped to the lane's key: that is the remove-then-recreate chain
// (token RESET_REMAINING freed it, a later round recreated it on
// device).  A slot owned by a DIFFERENT key means a later in-batch
// eviction took it over — this lane's write is stale, skip.
void gt_batch_commit_plan(void* bv, const int64_t* new_expire,
                          const uint8_t* removed) {
  Batch* b = (Batch*)bv;
  Table* t = b->table;
  GT_LOCK(t);
  b->committed = true;
  for (int32_t i : b->plan_order) {
    int32_t s = b->slot[i];
    if (s < 0) continue;
    if (t->pending_write[s] > 0) --t->pending_write[s];
    bool mine = t->slot_mapped[s] &&
                t->slot_key[s].compare(0, std::string::npos, b->key_ptr(i),
                                       b->key_len(i)) == 0;
    if (removed[i]) {
      if (mine) t->unmap_slot(s);
      continue;
    }
    if (mine) {
      // Negative expire is the narrow-wire "unchanged" sentinel
      // (ops/buckets.py unpack_output32): the kernel passed the slot's
      // pre-batch expiry through, so the host value is already right.
      if (new_expire[i] >= 0) t->expire_ms[s] = new_expire[i];
    } else if (!t->slot_mapped[s]) {
      t->remap(s, b->key_ptr(i), b->key_len(i), new_expire[i]);
    }
  }
}

// Grouped full plan: uniform duplicate groups collapse into round 0.
//
// A "uniform group" is every lane of one key whose request config
// (algorithm, behavior, hits, limit, duration, greg columns) is
// identical and carries no RESET_REMAINING (whose remove-recreate chain
// is inherently sequential).  Such a group needs no rounds at all: the
// kernel computes each occurrence's response in closed form from the
// occurrence index (ops/buckets.py analytic-duplicate math) and only
// the LAST occurrence scatters.  Lanes that do not qualify fall back to
// the round scheme starting at round 1.  This turns hot-key skew — the
// reference's thundering-herd case (its BATCHING exists for exactly
// this, architecture.md:19-25) — from O(max multiplicity) sequential
// kernel rounds into O(1).
//
// Outputs per lane: round_id, slot, exists, occ (occurrence index
// within a uniform group; 0 otherwise), write (1 when this lane's lane
// scatters state: the last occurrence of a uniform group, or every
// round-scheme lane).  Returns the round count.
int64_t gt_batch_plan_grouped(void* bv, const int32_t* algo,
                              const int32_t* behavior, const int64_t* hits,
                              const int64_t* limit, const int64_t* duration,
                              const int64_t* greg_e, const int64_t* greg_d,
                              int32_t reset_mask, int32_t* round_id,
                              int32_t* slots, uint8_t* exists, int32_t* occ,
                              uint8_t* write) {
  Batch* b = (Batch*)bv;
  Table* t = b->table;
  GT_LOCK(t);
  b->plan_order.clear();
  b->plan_order.reserve((size_t)b->n);

  // Group lanes by key, preserving first-appearance order.  Keys view
  // the borrowed packed buffer — no per-lane allocation — and members
  // live in a flat CSR layout (gid pass -> counting sort) instead of
  // one heap-allocated vector per group: at service batch sizes the
  // planner runs once per dispatch over tens of thousands of MOSTLY
  // UNIQUE keys, where per-group vectors cost one malloc per lane and
  // dominated the whole plan (native-service-loop profiling, PR 13).
  std::unordered_map<std::string_view, int32_t> group_of;
  group_of.reserve((size_t)b->n * 2);
  std::vector<int32_t> gid((size_t)b->n);
  std::vector<int32_t> gcount;
  gcount.reserve((size_t)b->n);
  int32_t n_groups = 0;
  for (int64_t i = 0; i < b->n; ++i) {
    std::string_view k(b->key_ptr(i), b->key_len(i));
    auto [it, fresh] = group_of.emplace(k, n_groups);
    if (fresh) {
      ++n_groups;
      gcount.push_back(0);
    }
    gid[(size_t)i] = it->second;
    ++gcount[(size_t)it->second];
  }
  // CSR offsets + member fill (members of one group stay in request
  // order — the occurrence index below depends on it).
  std::vector<int32_t> goff((size_t)n_groups + 1);
  goff[0] = 0;
  for (int32_t g = 0; g < n_groups; ++g) goff[(size_t)g + 1] = goff[(size_t)g] + gcount[(size_t)g];
  std::vector<int32_t> gmembers((size_t)b->n);
  {
    std::vector<int32_t> cursor(goff.begin(), goff.end() - 1);
    for (int64_t i = 0; i < b->n; ++i)
      gmembers[(size_t)cursor[(size_t)gid[(size_t)i]]++] = (int32_t)i;
  }

  std::unordered_map<int32_t, int> used0;  // slots written in round 0
  used0.reserve((size_t)n_groups * 2);
  // Seed the slot-owner map with round-0 groups so slow lanes detect
  // takeovers of (and chain onto) grouped slots.
  std::unordered_map<int32_t, std::string_view> slot_owner;
  slot_owner.reserve((size_t)b->n * 2);
  std::vector<int32_t> slow;  // lanes for the round scheme
  for (int32_t g = 0; g < n_groups; ++g) {
    const int32_t* mem = gmembers.data() + goff[(size_t)g];
    size_t g_size = (size_t)(goff[(size_t)g + 1] - goff[(size_t)g]);
    int32_t first = mem[0];
    bool uniform = (behavior[first] & reset_mask) == 0;
    for (size_t j = 1; uniform && j < g_size; ++j) {
      int32_t i = mem[j];
      uniform = algo[i] == algo[first] && behavior[i] == behavior[first] &&
                hits[i] == hits[first] && limit[i] == limit[first] &&
                duration[i] == duration[first] &&
                greg_e[i] == greg_e[first] && greg_d[i] == greg_d[first];
    }
    int64_t ev_before = t->evictions;
    auto [s, e] =
        t->lookup_or_assign(b->key_ptr(first), b->key_len(first), b->now_ms);
    b->slot[first] = s;
    b->exists[first] = e ? 1 : 0;
    b->resolved[first] = 1;
    // An eviction may have stolen the slot from a key with EARLIER
    // lanes in this batch; scheduling this group in round 0 would run
    // the create before the victim's lanes.  Demote to the slow path,
    // whose per-round slot-collision deferral orders it correctly.
    bool evicted = t->evictions != ev_before;
    if (uniform && !evicted && !used0.count(s)) {
      used0.emplace(s, 1);
      slot_owner[s] = std::string_view(b->key_ptr(first), b->key_len(first));
      ++t->pending_write[s];
      for (size_t j = 0; j < g_size; ++j) {
        int32_t i = mem[j];
        round_id[i] = 0;
        slots[i] = s;
        exists[i] = e ? 1 : 0;
        occ[i] = (int32_t)j;
        write[i] = (j + 1 == g_size) ? 1 : 0;
        b->slot[i] = s;
        if (write[i]) b->plan_order.push_back(i);
      }
    } else {
      for (size_t j = 0; j < g_size; ++j) slow.push_back(mem[j]);
    }
  }
  if (slow.empty()) return 1;

  // Round scheme for the leftovers, starting at round 1 (round 0 is the
  // grouped dispatch).  Same chaining/deferral rules as gt_batch_plan.
  std::sort(slow.begin(), slow.end());
  b->pending.assign(slow.begin(), slow.end());
  return plan_rounds(b, 1, round_id, slots, exists, occ, write, slot_owner);
}

void gt_batch_free(void* bv) {
  Batch* b = (Batch*)bv;
  // A planned-but-never-committed batch (error path) must release its
  // pending-write claims or the slots stay device-authoritative forever.
  // Locked: Python GC can run this from any thread while a younger
  // batch's plan is mid-flight on the same table.
  if (!b->committed) {
    Table* t = b->table;
    GT_LOCK(t);
    for (int32_t i : b->plan_order) {
      int32_t s = b->slot[i];
      if (s >= 0 && t->pending_write[s] > 0) --t->pending_write[s];
    }
  }
  delete b;
}

// ---------------------------------------------------------------------
// FNV-1 / FNV-1a 64 over a packed key batch (replicated_hash.go:31 uses
// fasthash/fnv1; host-side ring lookups hash every key of every batch).
void gt_fnv1_batch(const char* keys, const int64_t* offsets, int64_t n,
                   int32_t variant_1a, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const char* p = keys + offsets[i];
    const char* end = keys + offsets[i + 1];
    out[i] = variant_1a ? fnv1a64(p, end) : fnv1_64(p, end);
  }
}

}  // extern "C"

namespace {
// ---------------------------------------------------------------------
// Mesh planner: shard-bucket + per-shard grouped round planning + padded
// fill + decode/commit for a WHOLE device mesh in single C++ calls.
//
// parallel/mesh.py round 3 ran this as a serial Python loop over shards
// (hash -> argsort -> per-shard subset/make_columns -> NativeBatchPlanner
// -> padded array fill, then per-shard decode + commit) — ~2.7ms of the
// ~5.4ms host cost per 1000-lane service batch.  The reference serves
// its whole edge in compiled code (gubernator.go:116-227); this closes
// the same gap for the columnar ingress.  Call sequence per batch (all
// under the store lock, ColumnarPipeline discipline):
//
//   gt_mesh_begin(tables[S], keys, n)    -> handle + per-shard counts
//   gt_mesh_plan_grouped(h, cols, P, ..) -> padded [S,P] plan arrays,
//                                           pos[n] (lane -> padded idx)
//   ... device dispatch (Python/numpy packs the wire from the padded
//       arrays with vectorized ops) ...
//   gt_mesh_finish_{narrow,wide}(h, ..)  -> response columns in ORIGINAL
//                                           order + slot-table commit
//   gt_mesh_free(h)

struct MeshPlan {
  int64_t S = 0, n = 0, now_ms = 0, P = 0;
  std::vector<Table*> tables;
  std::vector<std::vector<char>> skeys;      // per-shard packed key bytes
  std::vector<std::vector<int64_t>> soffs;   // per-shard offsets [m+1]
  std::vector<std::vector<int32_t>> lanes;   // per-shard original lane ids
  std::vector<void*> batches;                // per-shard Batch* (plan phase)
  std::vector<std::vector<int32_t>> pslot;   // per-shard planned slots [m]
  std::vector<std::vector<int64_t>> pre_exp; // plan-time expiry snapshot [m]
};

}  // namespace

extern "C" {

// Phase 1: hash every key (fnv1a-64 % S, the static shardmap of
// parallel/mesh.py shard_of_key) and bucket keys/lanes per shard.
// Fills counts[S]; returns the handle.
void* gt_mesh_begin(void** tables, int64_t S, const char* keys,
                    const int64_t* offsets, int64_t n, int64_t now_ms,
                    int64_t* counts) {
  MeshPlan* mp = new MeshPlan();
  mp->S = S;
  mp->n = n;
  mp->now_ms = now_ms;
  mp->tables.assign((Table**)tables, (Table**)tables + S);
  mp->skeys.resize(S);
  mp->soffs.resize(S);
  mp->lanes.resize(S);
  mp->batches.assign(S, nullptr);
  mp->pslot.resize(S);
  mp->pre_exp.resize(S);

  std::vector<int32_t> shard_of((size_t)n);
  std::vector<int64_t> bytes_of((size_t)S, 0);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t h = fnv1a64(keys + offsets[i], keys + offsets[i + 1]);
    int32_t s = (int32_t)(h % (uint64_t)S);
    shard_of[i] = s;
    counts[s]++;
    bytes_of[s] += offsets[i + 1] - offsets[i];
  }
  for (int64_t s = 0; s < S; ++s) {
    mp->skeys[s].reserve((size_t)bytes_of[s]);
    mp->soffs[s].reserve((size_t)counts[s] + 1);
    mp->soffs[s].push_back(0);
    mp->lanes[s].reserve((size_t)counts[s]);
  }
  for (int64_t i = 0; i < n; ++i) {
    int32_t s = shard_of[i];
    mp->skeys[s].insert(mp->skeys[s].end(), keys + offsets[i],
                        keys + offsets[i + 1]);
    mp->soffs[s].push_back((int64_t)mp->skeys[s].size());
    mp->lanes[s].push_back((int32_t)i);
  }
  return mp;
}

// Phase 2: per-shard grouped planning straight into padded [S, P]
// row-major outputs (callers pre-fill slot with -1 and the rest with 0;
// this writes only lanes [0, m_s) of each row).  Column inputs are
// FULL-batch arrays indexed by original lane.  pos[i] = s*P + j maps
// each original lane to its padded position, so numpy fills value/cfg
// columns with one vectorized scatter per column.  Returns n_rounds
// (max over shards).
int64_t gt_mesh_plan_grouped(void* mpv, const int32_t* algo,
                             const int32_t* behavior, const int64_t* hits,
                             const int64_t* limit, const int64_t* duration,
                             const int64_t* greg_e, const int64_t* greg_d,
                             int32_t reset_mask, int64_t P, int32_t* slot,
                             int32_t* rid, uint8_t* exists, int32_t* occ,
                             uint8_t* write, int64_t* pos) {
  MeshPlan* mp = (MeshPlan*)mpv;
  mp->P = P;
  int64_t n_rounds = 1;
  std::vector<int32_t> a32, b32, rid_t, slot_t, occ_t;
  std::vector<int64_t> h64, l64, d64, ge64, gd64;
  std::vector<uint8_t> ex_t, wr_t;
  for (int64_t s = 0; s < mp->S; ++s) {
    int64_t m = (int64_t)mp->lanes[s].size();
    if (m == 0) continue;
    // One shard's whole plan (batch begin + grouped plan + pre_exp
    // snapshot) runs under that shard's table lock: atomic against a
    // concurrent older batch's finish on the same shard (the
    // overlapped-pipeline contract; the gt_batch_* calls below
    // re-enter the same recursive mutex).
    GT_LOCK(mp->tables[s]);
    // Gather this shard's column values into contiguous temporaries.
    a32.resize(m); b32.resize(m);
    h64.resize(m); l64.resize(m); d64.resize(m);
    ge64.resize(m); gd64.resize(m);
    for (int64_t j = 0; j < m; ++j) {
      int32_t i = mp->lanes[s][j];
      a32[j] = algo[i]; b32[j] = behavior[i];
      h64[j] = hits[i]; l64[j] = limit[i]; d64[j] = duration[i];
      ge64[j] = greg_e[i]; gd64[j] = greg_d[i];
    }
    rid_t.assign(m, 0); slot_t.resize(m); occ_t.assign(m, 0);
    ex_t.resize(m); wr_t.resize(m);
    void* b = gt_batch_begin(mp->tables[s], mp->skeys[s].data(),
                             mp->soffs[s].data(), m, mp->now_ms);
    mp->batches[s] = b;
    int64_t nr = gt_batch_plan_grouped(
        b, a32.data(), b32.data(), h64.data(), l64.data(), d64.data(),
        ge64.data(), gd64.data(), reset_mask, rid_t.data(), slot_t.data(),
        ex_t.data(), occ_t.data(), wr_t.data());
    if (nr > n_rounds) n_rounds = nr;
    Table* t = mp->tables[s];
    int64_t base = s * P;
    mp->pslot[s].assign(slot_t.begin(), slot_t.end());
    mp->pre_exp[s].resize(m);
    for (int64_t j = 0; j < m; ++j) {
      slot[base + j] = slot_t[j];
      rid[base + j] = rid_t[j];
      exists[base + j] = ex_t[j];
      occ[base + j] = occ_t[j];
      write[base + j] = wr_t[j];
      pos[mp->lanes[s][j]] = base + j;
      // Plan-time expiry snapshot for the narrow keep-sentinel decode
      // (models/shard.py decode_narrow passthrough semantics).
      int32_t sl = slot_t[j];
      mp->pre_exp[s][j] =
          (sl >= 0 && sl < t->capacity) ? t->expire_ms[sl] : 0;
    }
  }
  return n_rounds;
}

// Phase 3 (narrow wire): decode the packed i32[S, 4, P] device result,
// commit each shard's plan into its slot table, and scatter responses
// into ORIGINAL-order output columns.  Sentinels (ops/buckets.py
// apply_rounds32): row2/row3 are deltas from now; -1 = absolute 0,
// -2 = unchanged pass-through (reconstructed from the live table when
// the slot still maps this lane's key, else the plan-time snapshot).
void gt_mesh_finish_narrow(void* mpv, const int32_t* packed, int64_t now_ms,
                           int32_t* status, int64_t* remaining,
                           int64_t* reset_time) {
  MeshPlan* mp = (MeshPlan*)mpv;
  int64_t P = mp->P;
  std::vector<int64_t> ne;
  std::vector<uint8_t> rm;
  for (int64_t s = 0; s < mp->S; ++s) {
    int64_t m = (int64_t)mp->lanes[s].size();
    if (m == 0) continue;
    Table* t = mp->tables[s];
    GT_LOCK(t);
    Batch* b = (Batch*)mp->batches[s];
    const int32_t* row0 = packed + ((s * 4) + 0) * P;
    const int32_t* row1 = packed + ((s * 4) + 1) * P;
    const int32_t* row2 = packed + ((s * 4) + 2) * P;
    const int32_t* row3 = packed + ((s * 4) + 3) * P;
    ne.resize(m);
    rm.resize(m);
    for (int64_t j = 0; j < m; ++j) {
      int32_t orig = mp->lanes[s][j];
      status[orig] = row0[j] & 1;
      rm[j] = (uint8_t)((row0[j] >> 1) & 1);
      remaining[orig] = (int64_t)row1[j];
      int32_t d2 = row2[j];
      if (d2 == -1) {
        reset_time[orig] = 0;
      } else if (d2 == -2) {
        // Keep-sentinel: prefer the live table value while the slot
        // still maps this lane's key (decode_narrow defense in depth).
        int32_t sl = mp->pslot[s][j];
        bool mine = sl >= 0 && sl < t->capacity && t->slot_mapped[sl] &&
                    t->slot_key[sl].compare(0, std::string::npos,
                                            b->key_ptr(j), b->key_len(j)) == 0;
        reset_time[orig] = mine ? t->expire_ms[sl] : mp->pre_exp[s][j];
      } else {
        reset_time[orig] = (int64_t)d2 + now_ms;
      }
      int32_t d3 = row3[j];
      // -1 decodes to absolute 0 (removed/no-reset; commit_plan WRITES
      // expire_ms=0); -2 decodes to -1 so commit_plan skips the
      // already-correct host value (unpack_output32 parity).
      ne[j] = (d3 == -1) ? 0 : (d3 == -2 ? -1 : (int64_t)d3 + now_ms);
    }
    gt_batch_commit_plan(b, ne.data(), rm.data());
  }
}

// Phase 3 (wide wire): same shape over the packed i64[S, 4, P] result
// with absolute values (ops/buckets.py _pack_output rows).
void gt_mesh_finish_wide(void* mpv, const int64_t* packed, int32_t* status,
                         int64_t* remaining, int64_t* reset_time) {
  MeshPlan* mp = (MeshPlan*)mpv;
  int64_t P = mp->P;
  std::vector<int64_t> ne;
  std::vector<uint8_t> rm;
  for (int64_t s = 0; s < mp->S; ++s) {
    int64_t m = (int64_t)mp->lanes[s].size();
    if (m == 0) continue;
    GT_LOCK(mp->tables[s]);
    Batch* b = (Batch*)mp->batches[s];
    const int64_t* row0 = packed + ((s * 4) + 0) * P;
    const int64_t* row1 = packed + ((s * 4) + 1) * P;
    const int64_t* row2 = packed + ((s * 4) + 2) * P;
    const int64_t* row3 = packed + ((s * 4) + 3) * P;
    ne.resize(m);
    rm.resize(m);
    for (int64_t j = 0; j < m; ++j) {
      int32_t orig = mp->lanes[s][j];
      status[orig] = (int32_t)(row0[j] & 1);
      rm[j] = (uint8_t)((row0[j] >> 1) & 1);
      remaining[orig] = row1[j];
      reset_time[orig] = row2[j];
      ne[j] = row3[j];
    }
    gt_batch_commit_plan(b, ne.data(), rm.data());
  }
}

void gt_mesh_free(void* mpv) {
  MeshPlan* mp = (MeshPlan*)mpv;
  for (void* b : mp->batches)
    if (b) gt_batch_free(b);
  delete mp;
}

}  // extern "C"

namespace {
// ---------------------------------------------------------------------
// JSON edge: GetRateLimits request parser + response renderer.
//
// The gateway's hot path (gateway.py parse_columns/render_columns) is
// per-lane Python; at the reference's 1000-item request cap that costs
// more host time than the whole device dispatch.  This parser handles
// the gateway's actual wire shape — {"requests":[{flat objects}]} with
// proto3-JSON conventions (int64 as string, enums as names or ints) —
// and REFUSES anything fancier (escape sequences inside name/unique
// key, floats, nested values in known fields) by returning NULL so the
// Python path keeps full fidelity.  Outputs are kernel-ready columns
// plus packed hash keys (name + '_' + unique_key), per-lane validation
// codes (empty unique_key/name, bad enums — gubernator.go:142-152
// semantics), and (offset,len) spans of name/unique_key in the body so
// Python can materialize strings lazily for the rare slow lanes.

struct JsonBatch {
  std::vector<int32_t> algo, behavior;
  std::vector<int64_t> hits, limit, duration;
  std::vector<uint8_t> err;  // 0 ok, 1 empty uk, 2 empty name, 3 bad algo, 4 bad behavior
  std::string hk;
  std::vector<int64_t> hkoff;
  std::vector<int64_t> nspan, ukspan;  // 2*n: (off,len) into body
};

struct JsonCursor {
  const char* p;
  const char* end;
  bool ok = true;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }
  bool lit(char c) {
    ws();
    if (p < end && *p == c) { p++; return true; }
    return false;
  }
  // Raw string token; fails (ok=false) on escapes/EOF.  Returns
  // (offset, len) into the body.
  bool str(int64_t* off, int64_t* len, const char* base) {
    ws();
    if (p >= end || *p != '"') return false;
    p++;
    const char* s = p;
    while (p < end && *p != '"') {
      if (*p == '\\') { ok = false; return false; }
      p++;
    }
    if (p >= end) { ok = false; return false; }
    *off = s - base;
    *len = p - s;
    p++;
    return true;
  }
  // Integer, optionally quoted (proto3 int64-as-string).  Floats and
  // >18-digit magnitudes poison the cursor (Python fallback).
  bool integer(int64_t* out) {
    ws();
    bool quoted = p < end && *p == '"';
    if (quoted) p++;
    bool neg = false;
    if (p < end && (*p == '-' || *p == '+')) { neg = *p == '-'; p++; }
    if (p >= end || *p < '0' || *p > '9') { ok = false; return false; }
    int64_t v = 0;
    int digits = 0;
    while (p < end && *p >= '0' && *p <= '9') {
      v = v * 10 + (*p - '0');
      if (++digits > 18) { ok = false; return false; }
      p++;
    }
    if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) { ok = false; return false; }
    if (quoted) {
      if (p >= end || *p != '"') { ok = false; return false; }
      p++;
    }
    *out = neg ? -v : v;
    return true;
  }
  // Skip any JSON value (for unknown fields); handles escapes fine
  // since it never extracts content.
  bool skip_value() {
    ws();
    if (p >= end) { ok = false; return false; }
    char c = *p;
    if (c == '"') {
      p++;
      while (p < end && *p != '"') {
        if (*p == '\\') p++;
        p++;
      }
      if (p >= end) { ok = false; return false; }
      p++;
      return true;
    }
    if (c == '{' || c == '[') {
      char close = c == '{' ? '}' : ']';
      p++;
      int depth = 1;
      while (p < end && depth > 0) {
        char d = *p;
        if (d == '"') {
          p++;
          while (p < end && *p != '"') {
            if (*p == '\\') p++;
            p++;
          }
          if (p >= end) { ok = false; return false; }
        } else if (d == '{' || d == '[') depth++;
        else if (d == '}' || d == ']') depth--;
        p++;
      }
      (void)close;
      if (depth != 0) { ok = false; return false; }
      return true;
    }
    // number / true / false / null
    while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
           *p != '\t' && *p != '\n' && *p != '\r')
      p++;
    return true;
  }
};

bool key_is(const char* base, int64_t off, int64_t len, const char* name) {
  return (int64_t)strlen(name) == len && memcmp(base + off, name, len) == 0;
}

bool token_is(const char* base, int64_t off, int64_t len, const char* name) {
  return key_is(base, off, len, name);
}

}  // namespace

extern "C" {

void* gt_json_parse(const char* body, int64_t blen) {
  JsonCursor c{body, body + blen};
  auto* jb = new JsonBatch();
  auto fail = [&]() -> void* { delete jb; return nullptr; };

  if (!c.lit('{')) return fail();
  bool found_requests = false;
  if (c.lit('}')) {  // {} — still reject trailing garbage (json.loads parity)
    c.ws();
    if (c.p != c.end) return fail();
    jb->hkoff.push_back(0);
    return jb;
  }
  while (true) {
    int64_t koff, klen;
    if (!c.str(&koff, &klen, body)) return fail();
    if (!c.lit(':')) return fail();
    if (key_is(body, koff, klen, "requests")) {
      // Duplicate "requests" keys: json.loads is last-wins; appending
      // would double the batch.  Rare and weird — Python fallback.
      if (found_requests) return fail();
      found_requests = true;
      if (!c.lit('[')) return fail();
      if (!c.lit(']')) {
        while (true) {
          if (!c.lit('{')) return fail();
          int32_t algo = 0, behavior = 0;
          int64_t hits = 0, limit = 0, duration = 0;
          int64_t noff = 0, nlen = 0, uoff = 0, ulen = 0;
          uint8_t err = 0;
          if (!c.lit('}')) {
            while (true) {
              int64_t foff, flen;
              if (!c.str(&foff, &flen, body)) return fail();
              if (!c.lit(':')) return fail();
              if (key_is(body, foff, flen, "name")) {
                if (!c.str(&noff, &nlen, body)) return fail();
              } else if (key_is(body, foff, flen, "uniqueKey") ||
                         key_is(body, foff, flen, "unique_key")) {
                if (!c.str(&uoff, &ulen, body)) return fail();
              } else if (key_is(body, foff, flen, "hits")) {
                if (!c.integer(&hits)) return fail();
              } else if (key_is(body, foff, flen, "limit")) {
                if (!c.integer(&limit)) return fail();
              } else if (key_is(body, foff, flen, "duration")) {
                if (!c.integer(&duration)) return fail();
              } else if (key_is(body, foff, flen, "algorithm")) {
                c.ws();
                if (c.p < c.end && *c.p == '"') {
                  int64_t aoff, alen;
                  if (!c.str(&aoff, &alen, body)) return fail();
                  if (token_is(body, aoff, alen, "TOKEN_BUCKET")) algo = 0;
                  else if (token_is(body, aoff, alen, "LEAKY_BUCKET")) algo = 1;
                  else {
                    // quoted int (proto3 tolerance) or invalid
                    JsonCursor t{body + aoff, body + aoff + alen};
                    int64_t v;
                    if (t.integer(&v) && t.p == t.end && v >= 0 && v <= 1)
                      algo = (int32_t)v;
                    else if (err == 0) err = 3;
                  }
                } else {
                  int64_t v;
                  if (!c.integer(&v)) return fail();
                  if (v >= 0 && v <= 1) algo = (int32_t)v;
                  else if (err == 0) err = 3;
                }
              } else if (key_is(body, foff, flen, "behavior")) {
                c.ws();
                if (c.p < c.end && *c.p == '"') {
                  int64_t boff, blen2;
                  if (!c.str(&boff, &blen2, body)) return fail();
                  if (token_is(body, boff, blen2, "BATCHING")) behavior |= 0;
                  else if (token_is(body, boff, blen2, "NO_BATCHING")) behavior |= 1;
                  else if (token_is(body, boff, blen2, "GLOBAL")) behavior |= 2;
                  else if (token_is(body, boff, blen2, "DURATION_IS_GREGORIAN")) behavior |= 4;
                  else if (token_is(body, boff, blen2, "RESET_REMAINING")) behavior |= 8;
                  else if (token_is(body, boff, blen2, "MULTI_REGION")) behavior |= 16;
                  else {
                    JsonCursor t{body + boff, body + boff + blen2};
                    int64_t v;
                    if (t.integer(&v) && t.p == t.end) behavior = (int32_t)v;
                    else if (err == 0) err = 4;
                  }
                } else if (c.p < c.end && *c.p == '[') {
                  // list of flag names: rare — Python fallback
                  return fail();
                } else {
                  int64_t v;
                  if (!c.integer(&v)) return fail();
                  behavior = (int32_t)v;
                }
              } else {
                if (!c.skip_value()) return fail();
              }
              if (c.lit(',')) continue;
              if (c.lit('}')) break;
              return fail();
            }
          }
          // validation order matches gubernator.go:142-152 (unique_key first)
          if (err == 0 && ulen == 0) err = 1;
          if (err == 0 && nlen == 0) err = 2;
          jb->algo.push_back(algo);
          jb->behavior.push_back(behavior);
          jb->hits.push_back(hits);
          jb->limit.push_back(limit);
          jb->duration.push_back(duration);
          jb->err.push_back(err);
          jb->nspan.push_back(noff);
          jb->nspan.push_back(nlen);
          jb->ukspan.push_back(uoff);
          jb->ukspan.push_back(ulen);
          jb->hk.append(body + noff, (size_t)nlen);
          jb->hk.push_back('_');
          jb->hk.append(body + uoff, (size_t)ulen);
          if (c.lit(',')) continue;
          if (c.lit(']')) break;
          return fail();
        }
      }
    } else {
      if (!c.skip_value()) return fail();
    }
    if (c.lit(',')) continue;
    if (c.lit('}')) break;
    return fail();
  }
  c.ws();
  if (c.p != c.end || !c.ok || !found_requests) {
    if (!found_requests && c.ok && c.p == c.end) {
      jb->hkoff.push_back(0);
      return jb;  // no "requests" key: empty batch (gateway .get default)
    }
    return fail();
  }
  jb->hkoff.resize(jb->algo.size() + 1);
  int64_t acc = 0;
  for (size_t i = 0; i < jb->algo.size(); i++) {
    jb->hkoff[i] = acc;
    acc += jb->nspan[2 * i + 1] + 1 + jb->ukspan[2 * i + 1];
  }
  jb->hkoff[jb->algo.size()] = acc;
  return jb;
}

int64_t gt_json_n(void* j) { return (int64_t)((JsonBatch*)j)->algo.size(); }
int64_t gt_json_hk_bytes(void* j) { return (int64_t)((JsonBatch*)j)->hk.size(); }

void gt_json_fill(void* jv, int32_t* algo, int32_t* behavior, int64_t* hits,
                  int64_t* limit, int64_t* duration, uint8_t* err, char* hk,
                  int64_t* hkoff, int64_t* nspan, int64_t* ukspan) {
  auto* j = (JsonBatch*)jv;
  size_t n = j->algo.size();
  if (n) {
    memcpy(algo, j->algo.data(), n * sizeof(int32_t));
    memcpy(behavior, j->behavior.data(), n * sizeof(int32_t));
    memcpy(hits, j->hits.data(), n * sizeof(int64_t));
    memcpy(limit, j->limit.data(), n * sizeof(int64_t));
    memcpy(duration, j->duration.data(), n * sizeof(int64_t));
    memcpy(err, j->err.data(), n);
    memcpy(nspan, j->nspan.data(), 2 * n * sizeof(int64_t));
    memcpy(ukspan, j->ukspan.data(), 2 * n * sizeof(int64_t));
  }
  if (!j->hk.empty()) memcpy(hk, j->hk.data(), j->hk.size());
  memcpy(hkoff, j->hkoff.data(), (n + 1) * sizeof(int64_t));
}

void gt_json_free(void* j) { delete (JsonBatch*)j; }

// Render the GetRateLimits response body from result columns.  Lanes
// listed in ov_idx (sorted) splice in pre-rendered JSON objects
// (validation errors / forwarded lanes — rendered by Python, which
// keeps full metadata fidelity).  Single pass straight into the
// caller's buffer; `cap` must hold the worst case (a per-lane object
// is <= 129 bytes: 58 fixed + 11 status + 3x20 digits — callers
// budget 160).  Returns bytes written, or -1 if cap would overflow.
int64_t gt_json_render(const int32_t* status, const int64_t* limit,
                       const int64_t* remaining, const int64_t* reset,
                       int64_t n, const int64_t* ov_idx, int64_t n_ov,
                       const char* ov_buf, const int64_t* ov_off,
                       char* out, int64_t cap) {
  static const char* kStatus[] = {"UNDER_LIMIT", "OVER_LIMIT"};
  char* w = out;
  char* wend = out + cap;
  auto put = [&](const char* p, size_t len) {
    if (w + len > wend) return false;
    memcpy(w, p, len);
    w += len;
    return true;
  };
  auto lit = [&](const char* p) { return put(p, strlen(p)); };
  if (!lit("{\"responses\":[")) return -1;
  int64_t oi = 0;
  char tmp[24];
  for (int64_t i = 0; i < n; i++) {
    if (i && !lit(",")) return -1;
    if (oi < n_ov && ov_idx[oi] == i) {
      if (!put(ov_buf + ov_off[oi], (size_t)(ov_off[oi + 1] - ov_off[oi])))
        return -1;
      oi++;
      continue;
    }
    if (!lit("{\"status\":\"") || !lit(kStatus[status[i] & 1]) ||
        !lit("\",\"limit\":\"") ||
        !put(tmp, snprintf(tmp, sizeof tmp, "%lld", (long long)limit[i])) ||
        !lit("\",\"remaining\":\"") ||
        !put(tmp, snprintf(tmp, sizeof tmp, "%lld", (long long)remaining[i])) ||
        !lit("\",\"resetTime\":\"") ||
        !put(tmp, snprintf(tmp, sizeof tmp, "%lld", (long long)reset[i])) ||
        !lit("\"}"))
      return -1;
  }
  if (!lit("]}")) return -1;
  return (int64_t)(w - out);
}

}  // extern "C"

// ======================================================================
// GUBC ingress-frame parser (gt_frame_*): the public columnar front
// door's decode half in C++.
//
// A kind-5 ingress frame (wire.py "public columnar ingress") arrives
// through the epoll edge below; before any Python-level work runs, one
// native pass — entered via ctypes with the GIL released — validates
// the whole frame (magic/version/kind, string-column offset
// monotonicity, section lengths, algorithm range), computes the byte
// position of every column so Python wraps them as zero-copy numpy
// views, builds the packed hash keys (name + '_' + unique_key — the
// planner's input) with one scatter, and stamps per-lane validation
// codes (1 = empty unique_key, 2 = empty name; gubernator.go:142-152
// order).  The GIL only ever sees ready column buffers.  Anything
// malformed returns NULL and the numpy decode path reproduces the
// exact error wording.
//
// The scatter runs on the WORKER thread (parallel across workers,
// GIL-free), not the epoll thread: the epoll loop is the one shared
// resource every connection serializes on, so per-frame O(bytes) work
// there would re-create the convoy this edge exists to remove.
// ======================================================================

namespace {

struct FrameBatch {
  const char* body;  // caller-owned; must outlive the handle
  int64_t n = 0;
  int64_t name_off_pos = 0, name_blob_pos = 0, name_blob_len = 0;
  int64_t uk_off_pos = 0, uk_blob_pos = 0, uk_blob_len = 0;
};

// Little-endian u32 at an arbitrary (possibly unaligned) offset.
inline uint32_t frame_u32(const char* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

// Validate one string column at `pos`; fills off_pos/blob_pos/blob_len
// and returns the position past the column, or -1 when malformed
// (truncated, non-zero first offset, non-monotonic, length mismatch —
// the same checks wire._read_str_blob makes).
int64_t frame_str_col(const char* body, int64_t blen, int64_t pos, int64_t n,
                      int64_t* off_pos, int64_t* blob_pos, int64_t* blob_len) {
  if (pos + 4 > blen) return -1;
  int64_t bl = (int64_t)frame_u32(body + pos);
  pos += 4;
  if (pos + 4 * (n + 1) > blen) return -1;
  *off_pos = pos;
  const char* off = body + pos;
  pos += 4 * (n + 1);
  if (pos + bl > blen) return -1;
  if (n) {
    if (frame_u32(off) != 0) return -1;
    uint32_t prev = 0;
    for (int64_t i = 1; i <= n; i++) {
      uint32_t cur = frame_u32(off + 4 * i);
      if (cur < prev) return -1;
      prev = cur;
    }
    if ((int64_t)prev != bl) return -1;
  }
  *blob_pos = pos;
  *blob_len = bl;
  return pos + bl;
}

}  // namespace

extern "C" {

typedef struct {
  int64_t n;
  int64_t name_off_pos, name_blob_pos;
  int64_t uk_off_pos, uk_blob_pos;
  int64_t algo_pos, beh_pos, hits_pos, limit_pos, dur_pos;
  int64_t trace_pos;    // byte offset of the GTRC magic, -1 = absent
  int64_t trace_count;  // trailer entry count (32 bytes each)
  int64_t hk_bytes;     // packed hash-key buffer size for gt_frame_fill
} GtFrameInfo;

// Parse + validate a GUBC request frame of `kind`; fills *out and
// returns a handle for gt_frame_fill/gt_frame_free, or NULL when the
// frame is malformed (caller falls back to the Python decode for the
// exact error).  `body` must stay valid until gt_frame_free.
void* gt_frame_parse(const char* body, int64_t blen, int32_t kind,
                     GtFrameInfo* out) {
  if (blen < 10 || memcmp(body, "GUBC", 4) != 0) return nullptr;
  if ((uint8_t)body[4] != 1 || (uint8_t)body[5] != (uint8_t)kind)
    return nullptr;
  int64_t n = (int64_t)frame_u32(body + 6);
  // 2M lanes is far past every cap (PEER_COLUMNS_MAX_LANES = 16384);
  // bounding n keeps the size arithmetic below trivially overflow-free.
  if (n > (int64_t)2 * 1024 * 1024) return nullptr;
  FrameBatch fb;
  fb.body = body;
  fb.n = n;
  int64_t pos = 10;
  pos = frame_str_col(body, blen, pos, n, &fb.name_off_pos,
                      &fb.name_blob_pos, &fb.name_blob_len);
  if (pos < 0) return nullptr;
  pos = frame_str_col(body, blen, pos, n, &fb.uk_off_pos, &fb.uk_blob_pos,
                      &fb.uk_blob_len);
  if (pos < 0) return nullptr;
  if (pos + n * (4 + 4 + 8 + 8 + 8) > blen) return nullptr;
  out->algo_pos = pos;
  pos += 4 * n;
  out->beh_pos = pos;
  pos += 4 * n;
  out->hits_pos = pos;
  pos += 8 * n;
  out->limit_pos = pos;
  pos += 8 * n;
  out->dur_pos = pos;
  pos += 8 * n;
  // Algorithm range check (the public edge's one semantic column
  // check): out-of-range values reject the frame before the kernel
  // could see a garbage branch selector.
  for (int64_t i = 0; i < n; i++) {
    int32_t a;
    memcpy(&a, body + out->algo_pos + 4 * i, 4);
    if (a < 0 || a > 1) return nullptr;
  }
  out->trace_pos = -1;
  out->trace_count = 0;
  if (pos != blen) {
    // Only legal continuation: the GTRC trace trailer (wire.py).
    if (pos + 8 > blen || memcmp(body + pos, "GTRC", 4) != 0) return nullptr;
    out->trace_pos = pos;
    int64_t count = (int64_t)frame_u32(body + pos + 4);
    if (pos + 8 + count * 32 != blen) return nullptr;
    out->trace_count = count;
  }
  out->n = n;
  out->name_off_pos = fb.name_off_pos;
  out->name_blob_pos = fb.name_blob_pos;
  out->uk_off_pos = fb.uk_off_pos;
  out->uk_blob_pos = fb.uk_blob_pos;
  out->hk_bytes = fb.name_blob_len + n + fb.uk_blob_len;
  return new FrameBatch(fb);
}

// Build the packed hash keys (hk u8[hk_bytes] + hkoff i64[n+1]) and
// per-lane validation codes (err u8[n]: 1 empty unique_key, 2 empty
// name) from the frame the handle was parsed over.
void gt_frame_fill(void* h, uint8_t* hk, int64_t* hkoff, uint8_t* err) {
  auto* fb = (FrameBatch*)h;
  const char* body = fb->body;
  const char* noff = body + fb->name_off_pos;
  const char* uoff = body + fb->uk_off_pos;
  const char* nblob = body + fb->name_blob_pos;
  const char* ublob = body + fb->uk_blob_pos;
  int64_t w = 0;
  for (int64_t i = 0; i < fb->n; i++) {
    hkoff[i] = w;
    uint32_t n0 = frame_u32(noff + 4 * i), n1 = frame_u32(noff + 4 * (i + 1));
    uint32_t u0 = frame_u32(uoff + 4 * i), u1 = frame_u32(uoff + 4 * (i + 1));
    size_t nlen = n1 - n0, ulen = u1 - u0;
    memcpy(hk + w, nblob + n0, nlen);
    w += nlen;
    hk[w++] = '_';
    memcpy(hk + w, ublob + u0, ulen);
    w += ulen;
    err[i] = ulen == 0 ? 1 : (nlen == 0 ? 2 : 0);
  }
  hkoff[fb->n] = w;
}

void gt_frame_free(void* h) { delete (FrameBatch*)h; }

}  // extern "C"

// ======================================================================
// Native HTTP/1.1 edge (gt_http_*): the gateway's socket + framing
// layer in C++.
//
// The measured cost of the stdlib gateway (benchmarks/RESULTS.md cfg8
// decomposition) is ~1.1 ms/request of Python HTTP parsing plus a
// thread-per-connection model that convoys at 100-way concurrency on
// the GIL.  This edge replaces exactly that layer: N ACCEPTOR threads
// (GUBER_ACCEPTORS, SO_REUSEPORT — the kernel shards accepted
// connections across the group, so one serializing epoll loop stops
// being the ingress ceiling once the fast lane below removes Python
// from the per-frame path) each own accept/read/frame/write for their
// connections; parsed requests (method, path, body) queue to Python
// worker threads via gt_http_next (ctypes releases the GIL while they
// block), which run the UNCHANGED service path and hand response bytes
// back via gt_http_respond.  An optional AF_UNIX acceptor
// (GUBER_UDS_PATH) serves the same HTTP/1.1 + GUBC frames to same-host
// clients — the sidecar deployment the reference's k8s manifests imply
// — with zero TCP stack cost.  The reference serves its edge from
// compiled code too (the Go http runtime, daemon.go:194-239) — this is
// that capability, not a new protocol: same endpoints, same JSON, same
// errors.
//
// Idle behavior: each acceptor's epoll_wait blocks INDEFINITELY unless
// it owes a stall-sweep tick (an EOF'd conn with staged unread output)
// — response staging and shutdown wake it through its eventfd — so an
// idle daemon with N acceptors costs zero periodic wakeups instead of
// N x 5/s.
//
// Scope: HTTP/1.1 keep-alive, Content-Length bodies (no chunked
// REQUESTS — no client of this API sends them), no TLS (the daemon
// keeps the Python+ssl gateway when TLS is configured).  Bounded
// header/body sizes and a bounded ready queue (overflow answers 503
// without touching Python).
// ======================================================================

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr size_t kMaxHeaderBytes = 64 * 1024;
constexpr size_t kMaxBodyBytes = 32 * 1024 * 1024;  // > 1000-lane batches
constexpr size_t kMaxReadyQueue = 4096;

struct HttpServer;
struct HttpAcceptor;

struct HttpPending {
  uint64_t token;
  int fd;
  int acceptor;  // index into HttpServer::acceptors
  int method;    // 0 GET, 1 POST, 2 other
  bool keep_alive;
  std::string path;
  std::string body;
};

struct HttpConn {
  int fd = -1;
  HttpAcceptor* acc = nullptr;
  std::string in;
  // parsed-but-unanswered request count (pipelined clients): responses
  // write in arrival order because tokens are handed out in order and
  // the out buffer is appended in respond order per connection --
  // workers MAY finish out of order, so per-conn ordering is enforced
  // by queueing responses by token sequence.
  std::deque<uint64_t> awaiting;          // tokens awaiting response
  std::unordered_map<uint64_t, std::string> done;  // token -> response
  std::string out;
  size_t out_off = 0;
  bool want_close = false;
  // Read side hit EOF (client close or shutdown(SHUT_WR)): stop
  // watching EPOLLIN — level-triggered EOF would otherwise re-fire
  // every epoll_wait and spin the loop while responses are pending.
  bool saw_eof = false;
  // Write-stall clock for EOF'd conns with staged output: a peer that
  // half-closed and never reads would otherwise pin the fd + buffer
  // forever (no EPOLLIN events, EPOLLOUT never re-fires past a full
  // sndbuf).  Zero = not stalled; reset on write progress.
  std::chrono::steady_clock::time_point stall_start{};
};

// One listener + one epoll loop.  A REUSEPORT group is N of these on
// the same TCP port; the optional UDS lane is one more.  Connection
// state (conns map, response queue, stats) is guarded by the server's
// shared mutex — cross-thread response staging (Python workers, the
// fast-lane completion) must reach any acceptor — but each loop only
// ever TOUCHES its own conns, so the hot read/write path contends on
// the lock only at stage/close boundaries.
struct HttpAcceptor {
  HttpServer* srv = nullptr;
  int idx = 0;
  bool is_uds = false;
  int listen_fd = -1, epfd = -1, evfd = -1;
  std::thread loop;
  std::unordered_map<int, HttpConn*> conns;  // guarded by srv->mu
  // responses staged by Python / the fast lane, drained by this loop
  std::deque<std::pair<uint64_t, std::string>> resp_queue;  // srv->mu
  // stats (guarded by srv->mu): the per-acceptor fairness surface
  // (gubernator_ingress_acceptor_*).
  int64_t accepted = 0, requests = 0, ingress_frames = 0,
          ingress_lanes = 0, wakeups = 0;
};

struct HttpServer {
  std::vector<std::unique_ptr<HttpAcceptor>> acceptors;
  int port = 0;
  std::string uds_path;
  std::atomic<bool> stopping{false};

  std::mutex mu;
  std::condition_variable cv;
  std::deque<HttpPending*> ready;                  // parsed, for Python
  std::unordered_map<uint64_t, HttpPending*> inflight;  // token -> req
  // token -> (acceptor idx, fd): which conn answers the token.
  std::unordered_map<uint64_t, std::pair<int, int>> token_addr;
  uint64_t next_token = 1;
};

void http_close_conn(HttpServer* s, HttpConn* c) {
  HttpAcceptor* a = c->acc;
  epoll_ctl(a->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  {
    // Tokens of this connection that are still inflight must not write
    // to a reused fd: drop the mapping (responses get discarded).
    std::lock_guard<std::mutex> lk(s->mu);
    for (uint64_t t : c->awaiting) s->token_addr.erase(t);
    a->conns.erase(c->fd);
  }
  delete c;
}

void http_arm(HttpConn* c) {
  epoll_event ev{};
  ev.data.fd = c->fd;
  ev.events = (c->saw_eof ? 0u : EPOLLIN) |
              (c->out.size() > c->out_off ? EPOLLOUT : 0u);
  epoll_ctl(c->acc->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

// THE HTTP/1.1 response envelope of this edge — gt_http_respond, the
// ingress fast lane's kind-6/shed/error/shutdown fills and the Python
// edge's byte-identity contract all share this one builder, so a
// header change cannot silently fork the golden-tested response shape.
std::string http_envelope(int status, const char* reason,
                          const char* ctype, const char* body,
                          int64_t blen) {
  std::string r = "HTTP/1.1 " + std::to_string(status) + " " +
                  (reason && *reason ? reason : "OK") +
                  "\r\nContent-Type: " +
                  (ctype && *ctype ? ctype : "application/json") +
                  "\r\nContent-Length: " + std::to_string(blen) +
                  "\r\n\r\n";
  r.append(body, (size_t)blen);
  return r;
}

std::string http_simple_response(int code, const char* reason,
                                 const std::string& body, bool keep_alive) {
  std::string r = "HTTP/1.1 " + std::to_string(code) + " " + reason +
                  "\r\nContent-Type: application/json\r\nContent-Length: " +
                  std::to_string(body.size()) + "\r\n";
  if (!keep_alive) r += "Connection: close\r\n";
  r += "\r\n";
  r += body;
  return r;
}

// Stage one finished response onto its connection's acceptor queue and
// wake that loop.  The shared exit of gt_http_respond and the ingress
// fast lane's native response fill.
void http_stage_response(HttpServer* s, uint64_t token, std::string resp) {
  std::lock_guard<std::mutex> lk(s->mu);
  auto it = s->token_addr.find(token);
  if (it == s->token_addr.end()) return;  // conn died
  HttpAcceptor* a = s->acceptors[(size_t)it->second.first].get();
  a->resp_queue.emplace_back(token, std::move(resp));
  // After shutdown the eventfd is closed (and its number may be
  // reused elsewhere in the process) — never write it while
  // stopping.  Checked and written under s->mu: gt_http_shutdown
  // closes the fds under the same lock after setting stopping, so a
  // false read here guarantees the fd is still ours.
  if (!s->stopping.load()) {
    uint64_t one_u = 1;
    (void)!write(a->evfd, &one_u, 8);
  }
}

// Flush completed responses (in token order) into the conn's out buffer.
void http_stage_done(HttpConn* c) {
  while (!c->awaiting.empty()) {
    auto it = c->done.find(c->awaiting.front());
    if (it == c->done.end()) break;
    c->out += it->second;
    c->done.erase(it);
    c->awaiting.pop_front();
  }
}

// Parse as many complete requests as the buffer holds.  Returns false
// when the connection must die (malformed / oversize).
bool http_drain_input(HttpServer* s, HttpConn* c) {
  for (;;) {
    size_t he = c->in.find("\r\n\r\n");
    if (he == std::string::npos) {
      return c->in.size() <= kMaxHeaderBytes;
    }
    std::string_view head(c->in.data(), he);
    size_t line_end = head.find("\r\n");
    std::string_view req_line =
        head.substr(0, line_end == std::string_view::npos ? he : line_end);
    int method = 2;
    size_t path_off = 0;
    if (req_line.rfind("GET ", 0) == 0) { method = 0; path_off = 4; }
    else if (req_line.rfind("POST ", 0) == 0) { method = 1; path_off = 5; }
    if (method == 2) {
      if (req_line.find(' ') == std::string_view::npos) return false;
      // Parseable frame, unsupported method (HEAD/OPTIONS/PUT...):
      // answer 501 and close — a silent reset would make e.g. HEAD
      // health probes read as a hard backend failure.
      uint64_t t;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        t = s->next_token++;
        c->awaiting.push_back(t);
      }
      c->done[t] = http_simple_response(
          501, "Not Implemented",
          "{\"code\": 12, \"message\": \"method not implemented\"}", false);
      http_stage_done(c);
      c->want_close = true;
      c->in.clear();
      return true;
    }
    size_t path_end = req_line.find(' ', path_off);
    if (path_end == std::string_view::npos) return false;
    std::string path(req_line.substr(path_off, path_end - path_off));

    size_t content_len = 0;
    bool keep_alive = true;  // HTTP/1.1 default
    // header scan (case-insensitive names)
    size_t pos = (line_end == std::string_view::npos) ? he : line_end + 2;
    while (pos < he) {
      size_t eol = head.find("\r\n", pos);
      std::string_view line =
          head.substr(pos, (eol == std::string_view::npos ? he : eol) - pos);
      size_t colon = line.find(':');
      if (colon != std::string_view::npos) {
        std::string name(line.substr(0, colon));
        for (auto& ch : name) ch = (char)tolower((unsigned char)ch);
        std::string_view val = line.substr(colon + 1);
        while (!val.empty() && val.front() == ' ') val.remove_prefix(1);
        if (name == "content-length") {
          content_len = strtoull(std::string(val).c_str(), nullptr, 10);
        } else if (name == "connection") {
          std::string v(val);
          for (auto& ch : v) ch = (char)tolower((unsigned char)ch);
          if (v.find("close") != std::string::npos) keep_alive = false;
        }
      }
      if (eol == std::string_view::npos) break;
      pos = eol + 2;
    }
    if (content_len > kMaxBodyBytes) return false;
    size_t total = he + 4 + content_len;
    if (c->in.size() < total) return true;  // need more body bytes

    auto* p = new HttpPending;
    p->fd = c->fd;
    p->acceptor = c->acc->idx;
    p->method = method;
    p->keep_alive = keep_alive;
    p->path = std::move(path);
    p->body.assign(c->in, he + 4, content_len);
    c->in.erase(0, total);
    if (!keep_alive) c->want_close = true;

    std::unique_lock<std::mutex> lk(s->mu);
    p->token = s->next_token++;
    c->awaiting.push_back(p->token);
    ++c->acc->requests;
    if (s->ready.size() >= kMaxReadyQueue) {
      // Overload: answer 503 without touching Python — through the
      // ordered done-queue so pipelined responses never reorder.
      uint64_t t = p->token;
      lk.unlock();
      delete p;
      c->done[t] = http_simple_response(
          503, "Service Unavailable",
          "{\"code\": 14, \"message\": \"ingress queue full\"}", keep_alive);
      http_stage_done(c);
      continue;
    }
    s->token_addr[p->token] = {c->acc->idx, c->fd};
    s->ready.push_back(p);
    lk.unlock();
    s->cv.notify_one();
  }
}

// An EOF'd peer gets this long to drain its staged response before the
// conn is reclaimed.  Generous on purpose: it exists to bound abuse
// (half-close, never read), not to race legitimate slow readers or the
// multi-tens-of-seconds device rounds a response may still be awaiting
// (the clock only runs while bytes are STAGED and unread).
constexpr auto kEofWriteStall = std::chrono::seconds(30);

void http_loop(HttpAcceptor* a) {
  HttpServer* s = a->srv;
  epoll_event evs[64];
  // Adaptive idle timeout: block indefinitely unless the previous
  // sweep found an EOF-stalled conn whose deadline needs the clock
  // (response staging and shutdown wake us via the eventfd, so the
  // block costs nothing in liveness; the old fixed 200 ms tick burned
  // idle CPU per acceptor once there were N loops).
  bool need_tick = false;
  for (;;) {
    int n = epoll_wait(a->epfd, evs, 64, need_tick ? 200 : -1);
    if (s->stopping.load()) return;
    // Stage responses staged since the last wake.
    {
      std::unique_lock<std::mutex> lk(s->mu);
      ++a->wakeups;
      while (!a->resp_queue.empty()) {
        auto [token, resp] = std::move(a->resp_queue.front());
        a->resp_queue.pop_front();
        auto tf = s->token_addr.find(token);
        if (tf == s->token_addr.end()) continue;  // conn died
        auto ci = a->conns.find(tf->second.second);
        s->token_addr.erase(tf);
        if (ci == a->conns.end()) continue;
        HttpConn* c = ci->second;
        c->done[token] = std::move(resp);
        lk.unlock();
        http_stage_done(c);
        http_arm(c);
        lk.lock();
      }
    }
    for (int i = 0; i < n; ++i) {
      int fd = evs[i].data.fd;
      if (fd == a->evfd) {
        uint64_t junk;
        (void)!read(a->evfd, &junk, 8);
        continue;
      }
      if (fd == a->listen_fd) {
        for (;;) {
          int cfd = accept4(a->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (cfd < 0) break;
          if (!a->is_uds) {
            int one = 1;
            setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          }
          auto* c = new HttpConn;
          c->fd = cfd;
          c->acc = a;
          {
            std::lock_guard<std::mutex> lk(s->mu);
            a->conns[cfd] = c;
            ++a->accepted;
          }
          epoll_event ev{};
          ev.data.fd = cfd;
          ev.events = EPOLLIN;
          epoll_ctl(a->epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      HttpConn* c;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        auto it = a->conns.find(fd);
        if (it == a->conns.end()) continue;
        c = it->second;
      }
      bool dead = false;
      if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
        dead = true;
      }
      if (!dead && (evs[i].events & EPOLLIN)) {
        char buf[65536];
        bool eof = false;
        for (;;) {
          ssize_t r = read(fd, buf, sizeof buf);
          if (r > 0) {
            c->in.append(buf, (size_t)r);
            if (c->in.size() > kMaxHeaderBytes + kMaxBodyBytes) { dead = true; break; }
          } else if (r == 0) { eof = true; break; }
          else { if (errno != EAGAIN && errno != EWOULDBLOCK) dead = true; break; }
        }
        // Frame BEFORE honoring EOF: request bytes and the FIN often
        // arrive in one wakeup (a client that sends-and-closes, or
        // half-closes with shutdown(SHUT_WR) and still reads).  Killing
        // the conn on r==0 without draining would DROP fully-received
        // requests — observed as lost hits under load.
        if (!dead && !http_drain_input(s, c)) dead = true;
        if (!dead && eof) {
          // Half-close semantics: serve what was fully received, flush
          // any responses (the write side may still be open), then
          // close — the generic want_close check below fires once
          // everything is flushed, including on this same iteration
          // when nothing is pending.
          c->want_close = true;
          c->saw_eof = true;
        }
      }
      if (!dead && (evs[i].events & EPOLLOUT) && c->out.size() > c->out_off) {
        // MSG_NOSIGNAL: a peer that closed after its FIN must surface
        // as EPIPE, not SIGPIPE (Python ignores SIGPIPE; a non-Python
        // embedder would die).
        ssize_t w = send(fd, c->out.data() + c->out_off,
                         c->out.size() - c->out_off, MSG_NOSIGNAL);
        if (w > 0) {
          c->out_off += (size_t)w;
          if (c->out_off == c->out.size()) { c->out.clear(); c->out_off = 0; }
          c->stall_start = {};  // progress: restart the stall clock
        } else if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
          dead = true;
        }
      }
      if (!dead && c->want_close && c->awaiting.empty() && c->done.empty() &&
          c->out.size() == c->out_off) {
        dead = true;  // graceful close after the last response flushed
      }
      if (dead) http_close_conn(s, c);
      else http_arm(c);
    }
    {
      // Reclaim EOF'd conns whose peer stopped reading (see
      // HttpConn::stall_start).  O(conns) each wakeup; while any such
      // conn exists the loop keeps a 200 ms tick (need_tick), and
      // blocks indefinitely otherwise.
      //
      // Runs AFTER the fetched event batch above, never before: a
      // sweep close ahead of the loop would free an fd whose events
      // are still queued in evs[], and an accept() later in the SAME
      // batch can return that fd number for a brand-new conn — the
      // stale EPOLLHUP/EPOLLERR entry would then kill the reused fd
      // (round-5 advisor finding).  Sweeping here means every event
      // consumed belongs to the conn it was fetched for, and any
      // write progress in this batch has already reset stall_start
      // before the deadline check.
      auto now = std::chrono::steady_clock::now();
      std::vector<HttpConn*> stalled;
      need_tick = false;
      {
        std::lock_guard<std::mutex> lk(s->mu);
        for (auto& [fd, c] : a->conns) {
          if (!c->saw_eof || c->out.size() <= c->out_off) continue;
          if (c->stall_start == std::chrono::steady_clock::time_point{}) {
            c->stall_start = now;
            need_tick = true;
          } else if (now - c->stall_start > kEofWriteStall) {
            stalled.push_back(c);
          } else {
            need_tick = true;
          }
        }
      }
      for (auto* c : stalled) http_close_conn(s, c);
    }
  }
}

void http_destroy_acceptors(HttpServer* s) {
  for (auto& a : s->acceptors) {
    if (a->listen_fd >= 0) close(a->listen_fd);
    if (a->epfd >= 0) close(a->epfd);
    if (a->evfd >= 0) close(a->evfd);
  }
  if (!s->uds_path.empty()) unlink(s->uds_path.c_str());
}

}  // namespace

extern "C" {

typedef struct {
  uint64_t token;
  int32_t method;
  int32_t path_len;
  int64_t body_len;
  const char* path;
  const char* body;
} GtHttpReq;

// Start the edge: `n_acceptors` SO_REUSEPORT TCP listeners on
// host:port (1 = the classic single loop, no REUSEPORT needed), plus
// one AF_UNIX listener at `uds_path` when non-empty (same HTTP/1.1 +
// frame protocol; a stale socket file is unlinked first — the daemon
// owns its configured path).  Returns NULL when any bind fails.
void* gt_http_start(const char* host, int port, int n_acceptors,
                    const char* uds_path) {
  auto* s = new HttpServer;
  if (n_acceptors < 1) n_acceptors = 1;
  int bound_port = port;
  for (int i = 0; i < n_acceptors; ++i) {
    auto a = std::make_unique<HttpAcceptor>();
    a->srv = s;
    a->idx = i;
    a->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    int one = 1;
    setsockopt(a->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    if (n_acceptors > 1) {
#ifdef SO_REUSEPORT
      if (setsockopt(a->listen_fd, SOL_SOCKET, SO_REUSEPORT, &one,
                     sizeof one) != 0) {
        close(a->listen_fd);
        http_destroy_acceptors(s);
        delete s;
        return nullptr;
      }
#else
      close(a->listen_fd);
      http_destroy_acceptors(s);
      delete s;
      return nullptr;
#endif
    }
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)bound_port);
    addr.sin_addr.s_addr =
        host && *host ? inet_addr(host) : htonl(INADDR_LOOPBACK);
    if (bind(a->listen_fd, (sockaddr*)&addr, sizeof addr) != 0 ||
        listen(a->listen_fd, 512) != 0) {
      close(a->listen_fd);
      http_destroy_acceptors(s);
      delete s;
      return nullptr;
    }
    if (i == 0) {
      // Port 0 resolves at the first bind; the rest of the REUSEPORT
      // group binds the resolved port.
      socklen_t alen = sizeof addr;
      getsockname(a->listen_fd, (sockaddr*)&addr, &alen);
      bound_port = ntohs(addr.sin_port);
      s->port = bound_port;
    }
    s->acceptors.push_back(std::move(a));
  }
  if (uds_path && *uds_path) {
    sockaddr_un ua{};
    if (strlen(uds_path) >= sizeof ua.sun_path) {
      http_destroy_acceptors(s);
      delete s;
      return nullptr;
    }
    auto a = std::make_unique<HttpAcceptor>();
    a->srv = s;
    a->idx = (int)s->acceptors.size();
    a->is_uds = true;
    a->listen_fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    ua.sun_family = AF_UNIX;
    strncpy(ua.sun_path, uds_path, sizeof ua.sun_path - 1);
    unlink(uds_path);  // the daemon owns its configured path
    if (bind(a->listen_fd, (sockaddr*)&ua, sizeof ua) != 0 ||
        listen(a->listen_fd, 512) != 0) {
      close(a->listen_fd);
      http_destroy_acceptors(s);
      delete s;
      return nullptr;
    }
    s->uds_path = uds_path;
    s->acceptors.push_back(std::move(a));
  }
  for (auto& a : s->acceptors) {
    a->epfd = epoll_create1(0);
    a->evfd = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.data.fd = a->listen_fd;
    ev.events = EPOLLIN;
    epoll_ctl(a->epfd, EPOLL_CTL_ADD, a->listen_fd, &ev);
    ev.data.fd = a->evfd;
    ev.events = EPOLLIN;
    epoll_ctl(a->epfd, EPOLL_CTL_ADD, a->evfd, &ev);
  }
  for (auto& a : s->acceptors) {
    a->loop = std::thread(http_loop, a.get());
  }
  return s;
}

int gt_http_port(void* sv) { return ((HttpServer*)sv)->port; }

int gt_http_acceptor_count(void* sv) {
  return (int)((HttpServer*)sv)->acceptors.size();
}

// Per-acceptor stats: out is i64[count * 7] rows of {is_uds, accepted
// conns, requests, ingress frames (fast lane), ingress lanes, epoll
// wakeups, live conns}.
void gt_http_acceptor_stats(void* sv, int64_t* out) {
  auto* s = (HttpServer*)sv;
  std::lock_guard<std::mutex> lk(s->mu);
  for (size_t i = 0; i < s->acceptors.size(); ++i) {
    HttpAcceptor* a = s->acceptors[i].get();
    out[i * 7 + 0] = a->is_uds ? 1 : 0;
    out[i * 7 + 1] = a->accepted;
    out[i * 7 + 2] = a->requests;
    out[i * 7 + 3] = a->ingress_frames;
    out[i * 7 + 4] = a->ingress_lanes;
    out[i * 7 + 5] = a->wakeups;
    out[i * 7 + 6] = (int64_t)a->conns.size();
  }
}

// Blocks (GIL released by ctypes) until a request is ready, the server
// stops (-1), or timeout_ms elapses (0).  1 = *out filled; pointers
// stay valid until gt_http_respond/gt_ingress_submit for that token.
int gt_http_next(void* sv, int64_t timeout_ms, GtHttpReq* out) {
  auto* s = (HttpServer*)sv;
  std::unique_lock<std::mutex> lk(s->mu);
  if (!s->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                      [&] { return !s->ready.empty() || s->stopping.load(); })) {
    return 0;
  }
  if (s->ready.empty()) return -1;  // stopping
  HttpPending* p = s->ready.front();
  s->ready.pop_front();
  s->inflight[p->token] = p;
  out->token = p->token;
  out->method = p->method;
  out->path_len = (int32_t)p->path.size();
  out->body_len = (int64_t)p->body.size();
  out->path = p->path.c_str();
  out->body = p->body.data();
  return 1;
}

void gt_http_respond(void* sv, uint64_t token, int status, const char* reason,
                     const char* ctype, const char* body, int64_t body_len) {
  auto* s = (HttpServer*)sv;
  std::string resp = http_envelope(status, reason, ctype, body, body_len);
  {
    std::lock_guard<std::mutex> lk(s->mu);
    auto it = s->inflight.find(token);
    if (it != s->inflight.end()) {
      delete it->second;
      s->inflight.erase(it);
    }
  }
  http_stage_response(s, token, std::move(resp));
}

// Two-phase teardown (shutdown -> free): workers may still be blocked
// in gt_http_next or finishing a long device round that will call
// gt_http_respond — the HttpServer must stay allocated until every
// worker has returned.  gt_http_shutdown stops traffic and joins the
// epoll threads; the caller joins its workers; gt_http_free releases.
void gt_http_shutdown(void* sv) {
  auto* s = (HttpServer*)sv;
  s->stopping.store(true);
  s->cv.notify_all();
  for (auto& a : s->acceptors) {
    uint64_t one_u = 1;
    (void)!write(a->evfd, &one_u, 8);
  }
  for (auto& a : s->acceptors) a->loop.join();
  std::lock_guard<std::mutex> lk(s->mu);
  for (auto& a : s->acceptors) {
    for (auto& [fd, c] : a->conns) {
      close(fd);
      delete c;
    }
    a->conns.clear();
  }
  http_destroy_acceptors(s);
}

void gt_http_free(void* sv) {
  auto* s = (HttpServer*)sv;
  for (auto& [t, p] : s->inflight) delete p;
  for (auto* p : s->ready) delete p;
  delete s;
}

}  // extern "C"

// ======================================================================
// Native ingress service loop (gt_ingress_*): the GIL-free hot path
// between the socket and the device pipeline.
//
// PR 8 proved the REQUEST half (gt_frame_parse: one GIL-released pass
// from bytes to kernel-ready columns); this closes the LOOP.  The
// steady-state columnar front door — accept -> GUBC kind-5 validate ->
// FNV-1 hash + ring-route (the native twin of
// hash_ring.get_batch_codes) -> enqueue into the ingress ring ->
// kind-6 response fill -> write — now runs entirely in C++ on worker
// threads, with Python touching ONE take/dispatch/complete round per
// BATCH (many coalesced frames), exactly the reference's shape: its
// whole request loop is compiled Go with no interpreter anywhere
// (daemon.go / the gRPC service surface).
//
// Contract with the Python tier:
//   gt_ingress_submit(server, batcher, token) — called by a gateway
//     worker right after gt_http_next handed it a POST whose body
//     magic-sniffs as a kind-5 frame.  GIL released for the whole call
//     (ctypes).  Returns 0 = handled natively (enqueued, or shed with
//     a staged 429); > 0 = fall back to the Python path (malformed
//     frame, trace trailer, slow behavior bits, validation-error
//     lanes, remote-owned lanes, disabled/oversize) — the HttpPending
//     is untouched and Python serves the request exactly as before,
//     which is what keeps every error's wording and the mixed-version
//     interop byte-identical.
//   gt_ingress_take — the Python pump thread blocks here (GIL
//     released) and receives ONE coalesced batch: contiguous
//     kernel-ready column arrays spanning every pending frame (plus
//     the FNV-1 hashes the route already computed, for the hot-key
//     sketch, and name/uk columns for the tenant fold) — zero-copy
//     numpy views, no per-frame Python.
//   gt_ingress_complete — after the device round, one call fans the
//     result arrays back out: per frame, slice -> kind-6 frame encode
//     -> HTTP wrap -> stage on the owning acceptor.  The bytes are
//     identical to wire.encode_ingress_result_frame for the
//     no-override/no-owner case (golden-tested), so a client cannot
//     tell the native loop from the PR 8 path.
//
// Lanes that need Python semantics (GLOBAL replication, MULTI_REGION
// queueing, Gregorian durations, per-lane validation errors, sampled
// traces, remote owners) make the WHOLE frame fall back: correctness
// never depends on the fast lane, it only removes interpreter time
// from the already-columnar common case.  NO_BATCHING lanes are the
// express-lane exception (PR 14): with GUBER_EXPRESS on they stay
// native and jump the queue (express_mask / xq below) — the bit means
// "skip coalescing waits", which is satisfiable entirely in this loop
// — and only fall back (the PR 13 behavior) when the lane is off.
// ======================================================================

namespace {

// Strict UTF-8 validation (RFC 3629: no surrogates, no overlongs, max
// U+10FFFF) — parity with the Python decode edge's .decode("utf-8"),
// which 400s invalid client strings before they can 500 deep in a slow
// lane.
bool utf8_valid(const char* p, size_t len) {
  const unsigned char* s = (const unsigned char*)p;
  const unsigned char* end = s + len;
  while (s < end) {
    unsigned char c = *s;
    if (c < 0x80) { ++s; continue; }
    int extra;
    unsigned int cp;
    if ((c & 0xE0) == 0xC0) { extra = 1; cp = c & 0x1F; }
    else if ((c & 0xF0) == 0xE0) { extra = 2; cp = c & 0x0F; }
    else if ((c & 0xF8) == 0xF0) { extra = 3; cp = c & 0x07; }
    else return false;
    if (s + 1 + extra > end) return false;
    for (int i = 1; i <= extra; ++i) {
      if ((s[i] & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (s[i] & 0x3F);
    }
    if (extra == 1 && cp < 0x80) return false;
    if (extra == 2 && (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF)))
      return false;
    if (extra == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
    s += 1 + extra;
  }
  return true;
}

// Immutable ring snapshot, swapped atomically under the batcher lock
// (set_peers pushes a new one; in-flight submits keep their reference).
struct RingSnap {
  std::vector<uint64_t> vh;     // sorted vnode hashes
  std::vector<uint8_t> vself;   // vnode owner == this daemon
  bool all_self = false;        // every peer is self: skip the search
  int hash_variant = 0;         // 0 = fnv1, 1 = fnv1a (hash_ring)
};

struct IngressFrame {
  HttpServer* srv;
  uint64_t token;
  int acceptor;
  bool keep_alive;
  bool express = false;  // NO_BATCHING lane(s): rides the express queue
  std::string body;   // owns the frame bytes; columns view into it
  GtFrameInfo info;
  int64_t n;
  std::string hk;                 // packed hash keys (name + '_' + uk)
  std::vector<int64_t> hkoff;     // n+1
  std::vector<uint64_t> hashes;   // ring hash per lane
  std::chrono::steady_clock::time_point arrival;
  int64_t parse_ns;
};

struct TakenBatch {
  std::vector<IngressFrame*> frames;
  int64_t n = 0;
  std::vector<int32_t> algo, beh;
  std::vector<int64_t> hits, limit, dur;
  std::string hk;
  std::vector<int64_t> hkoff;
  std::vector<uint64_t> hashes;
  std::string name_blob, uk_blob;
  std::vector<int64_t> name_off, uk_off;
  std::vector<int64_t> frame_lanes, frame_age_us;
  int64_t parse_ns_total = 0;
};

struct IngressBatcher {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<IngressFrame*> q;
  // Express queue (the millisecond express lane): frames carrying a
  // NO_BATCHING lane jump here and every take() serves it FIRST, so
  // the lowest-latency request class never waits behind coalesced
  // bulk frames.  Same shed bound, same batch coalescing — only the
  // service order differs.
  std::deque<IngressFrame*> xq;
  int64_t pending_lanes = 0;
  bool stopping = false;
  // config (gt_ingress_set_ring)
  bool enabled = false;
  std::shared_ptr<const RingSnap> ring;
  int64_t cap_lanes = 0;       // shed bound; 0 = unbounded
  int64_t max_frame_lanes = 16384;
  int32_t behavior_mask = 0;   // any set bit -> Python fallback
  int32_t express_mask = 0;    // any set bit -> express queue (0 = off)
  // counters
  int64_t frames = 0, lanes = 0, batches = 0;
  int64_t shed_frames = 0, shed_lanes = 0;
  int64_t fallbacks = 0;
  int64_t express_frames = 0, express_lanes = 0;
};

void ingress_free_frame(IngressFrame* f) { delete f; }

}  // namespace

extern "C" {

typedef struct {
  int64_t n, n_frames;
  const int32_t* algo;
  const int32_t* beh;
  const int64_t* hits;
  const int64_t* limit;
  const int64_t* duration;
  const char* hk;
  const int64_t* hkoff;
  int64_t hk_bytes;
  const uint64_t* hashes;
  const char* name_blob;
  const int64_t* name_off;
  int64_t name_bytes;
  const char* uk_blob;
  const int64_t* uk_off;
  int64_t uk_bytes;
  const int64_t* frame_lanes;
  const int64_t* frame_age_us;
  int64_t parse_ns_total;
} GtTakenInfo;

void* gt_ingress_new(void) { return new IngressBatcher; }

// Push the route/config snapshot (service.set_peers): sorted vnode
// hashes + per-vnode self bits (the integer-owner-code pass of
// hash_ring.get_batch_codes collapsed to the one question the fast
// lane asks: "is every lane owned here?"), plus the knobs.  enabled=0
// makes every submit fall back (handoff windows, non-default hash_fn,
// GUBER_NATIVE_INGRESS=0).
void gt_ingress_set_ring(void* bv, const uint64_t* vh, const uint8_t* vself,
                         int64_t nv, int32_t all_self, int32_t enabled,
                         int64_t cap_lanes, int64_t max_frame_lanes,
                         int32_t behavior_mask, int32_t hash_variant,
                         int32_t express_mask) {
  auto* b = (IngressBatcher*)bv;
  auto snap = std::make_shared<RingSnap>();
  snap->vh.assign(vh, vh + nv);
  snap->vself.assign(vself, vself + nv);
  snap->all_self = all_self != 0;
  snap->hash_variant = hash_variant;
  std::lock_guard<std::mutex> lk(b->mu);
  b->ring = std::move(snap);
  b->enabled = enabled != 0;
  b->cap_lanes = cap_lanes;
  b->max_frame_lanes = max_frame_lanes;
  b->behavior_mask = behavior_mask;
  b->express_mask = express_mask;
}

// The fast-lane entry (see the banner for the contract).  Returns 0 =
// handled natively; >0 = Python fallback reason (1 malformed/bad-utf8,
// 2 trace trailer, 3 empty/oversize, 4 slow behavior bits, 5
// validation-error lanes, 6 disabled, 7 remote-owned lanes); -1 =
// unknown token.
int gt_ingress_submit(void* sv, void* bv, uint64_t token) {
  auto* s = (HttpServer*)sv;
  auto* b = (IngressBatcher*)bv;
  HttpPending* p;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    auto it = s->inflight.find(token);
    if (it == s->inflight.end()) return -1;
    p = it->second;
  }
  bool enabled;
  std::shared_ptr<const RingSnap> ring;
  int64_t max_frame_lanes;
  int32_t behavior_mask;
  int32_t express_mask;
  {
    std::lock_guard<std::mutex> lk(b->mu);
    enabled = b->enabled && !b->stopping;
    ring = b->ring;
    max_frame_lanes = b->max_frame_lanes;
    behavior_mask = b->behavior_mask;
    express_mask = b->express_mask;
  }
  auto bump_fallback = [&](int code) {
    std::lock_guard<std::mutex> lk(b->mu);
    ++b->fallbacks;
    return code;
  };
  if (!enabled || !ring) return bump_fallback(6);
  auto t0 = std::chrono::steady_clock::now();
  GtFrameInfo info;
  void* h = gt_frame_parse(p->body.data(), (int64_t)p->body.size(), 5, &info);
  if (!h) return bump_fallback(1);  // Python owns the 400 wording
  gt_frame_free(h);                 // positions captured in `info`
  if (info.trace_count > 0) return bump_fallback(2);  // sampled: span links
  int64_t n = info.n;
  if (n == 0 || n > max_frame_lanes) return bump_fallback(3);
  const char* body = p->body.data();
  // Slow behavior bits (GLOBAL / MULTI_REGION / Gregorian — and
  // NO_BATCHING when the express lane is off) need the Python
  // router's semantics.  With the express lane on, NO_BATCHING lanes
  // instead flag the frame for the express queue below.
  bool xpress = false;
  for (int64_t i = 0; i < n; ++i) {
    int32_t bh;
    memcpy(&bh, body + info.beh_pos + 4 * i, 4);
    if (bh & behavior_mask) return bump_fallback(4);
    if (bh & express_mask) xpress = true;
  }
  // Build the packed hash keys + validation codes (the gt_frame_fill
  // pass, inlined so an error lane can bail early), then the UTF-8
  // parity check the Python decode edge makes.
  auto frame = std::make_unique<IngressFrame>();
  frame->hk.reserve((size_t)info.hk_bytes);
  frame->hkoff.resize((size_t)n + 1);
  const char* noff = body + info.name_off_pos;
  const char* uoff = body + info.uk_off_pos;
  const char* nblob = body + info.name_blob_pos;
  const char* ublob = body + info.uk_blob_pos;
  for (int64_t i = 0; i < n; ++i) {
    frame->hkoff[(size_t)i] = (int64_t)frame->hk.size();
    uint32_t n0 = frame_u32(noff + 4 * i), n1 = frame_u32(noff + 4 * (i + 1));
    uint32_t u0 = frame_u32(uoff + 4 * i), u1 = frame_u32(uoff + 4 * (i + 1));
    if (u1 == u0 || n1 == n0) return bump_fallback(5);  // validation lanes
    frame->hk.append(nblob + n0, n1 - n0);
    frame->hk.push_back('_');
    frame->hk.append(ublob + u0, u1 - u0);
  }
  frame->hkoff[(size_t)n] = (int64_t)frame->hk.size();
  {
    uint32_t ntot = frame_u32(noff + 4 * n), utot = frame_u32(uoff + 4 * n);
    if (!utf8_valid(nblob, ntot) || !utf8_valid(ublob, utot))
      return bump_fallback(1);
  }
  // FNV-1 hash + ring-route: the native ownership-code pass.  Any lane
  // owned elsewhere -> the Python router (it groups/forwards).
  frame->hashes.resize((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    const char* kp = frame->hk.data() + frame->hkoff[(size_t)i];
    const char* ke = frame->hk.data() + frame->hkoff[(size_t)i + 1];
    frame->hashes[(size_t)i] =
        ring->hash_variant ? fnv1a64(kp, ke) : fnv1_64(kp, ke);
  }
  if (!ring->all_self) {
    const auto& vh = ring->vh;
    if (vh.empty()) return bump_fallback(7);
    for (int64_t i = 0; i < n; ++i) {
      size_t idx = (size_t)(std::lower_bound(vh.begin(), vh.end(),
                                             frame->hashes[(size_t)i]) -
                            vh.begin());
      if (idx == vh.size()) idx = 0;
      if (!ring->vself[idx]) return bump_fallback(7);
    }
  }
  frame->srv = s;
  frame->token = token;
  frame->acceptor = p->acceptor;
  frame->keep_alive = p->keep_alive;
  frame->n = n;
  frame->info = info;
  frame->arrival = t0;
  frame->parse_ns = (int64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
                        std::chrono::steady_clock::now() - t0)
                        .count();
  // Shed bound + enqueue decided under ONE batcher lock: a submit
  // losing the race with gt_ingress_stop must NOT push a frame after
  // stop drained the queue — no pump would remain to answer it and
  // the client would hang to its own deadline.  The stopping verdict
  // here keeps the HttpPending intact, so the request falls back to
  // the Python path (which owns the shutdown 503).
  int64_t queued = 0, cap = 0;
  int verdict;  // 0 = enqueued, 1 = shed, 2 = stopping/disabled
  {
    std::lock_guard<std::mutex> lk(b->mu);
    if (b->stopping || !b->enabled) {
      verdict = 2;
    } else {
      queued = b->pending_lanes;
      cap = b->cap_lanes;
      if (cap > 0 && queued + n > cap) {
        verdict = 1;
        ++b->shed_frames;
        b->shed_lanes += n;
      } else {
        verdict = 0;
        b->pending_lanes += n;
        ++b->frames;
        b->lanes += n;
        // The columns keep viewing the moved body; ownership transfers
        // to the queue inside the lock so no stop() can slip between.
        frame->body = std::move(p->body);
        frame->express = xpress;
        if (xpress) {
          ++b->express_frames;
          b->express_lanes += n;
          b->xq.push_back(frame.release());
        } else {
          b->q.push_back(frame.release());
        }
      }
    }
  }
  if (verdict == 2) return bump_fallback(6);
  if (verdict == 1) {
    // Answer the 429 natively, byte-identical to the Python
    // IngressShedError triplet, without queueing work the device
    // cannot serve inside any useful deadline.
    std::string msg =
        "{\"code\": 2, \"message\": \"ingress queue saturated (" +
        std::to_string(queued) + " lanes queued, cap " +
        std::to_string(cap) + "); retry with backoff\"}";
    std::string resp =
        http_envelope(429, "Error", "application/json", msg.data(),
                      (int64_t)msg.size());
    {
      std::lock_guard<std::mutex> lk(s->mu);
      s->inflight.erase(token);
    }
    delete p;
    http_stage_response(s, token, std::move(resp));
    return 0;
  }
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->inflight.erase(token);
    if ((size_t)p->acceptor < s->acceptors.size()) {
      HttpAcceptor* a = s->acceptors[(size_t)p->acceptor].get();
      ++a->ingress_frames;
      a->ingress_lanes += n;
    }
  }
  delete p;
  b->cv.notify_one();
  return 0;
}

// Python pump: block (GIL released) for one coalesced batch of up to
// max_lanes lanes (the first frame always fits — frames are capped at
// max_frame_lanes <= any sane take bound).  1 = *out filled, handle in
// *out_tb (pointers valid until gt_ingress_complete/fail); 0 =
// timeout; -1 = stopping and drained.
int gt_ingress_take(void* bv, int64_t max_lanes, int64_t timeout_ms,
                    void** out_tb, GtTakenInfo* out) {
  auto* b = (IngressBatcher*)bv;
  auto tb = std::make_unique<TakenBatch>();
  {
    std::unique_lock<std::mutex> lk(b->mu);
    if (!b->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
          return !b->q.empty() || !b->xq.empty() || b->stopping;
        })) {
      return 0;
    }
    if (b->q.empty() && b->xq.empty()) return -1;  // stopping
    // Express frames first AND pure (the lane's whole point: a
    // NO_BATCHING frame never waits behind coalesced bulk backlog —
    // an express take must not keep filling from the bulk queue, or
    // the express response would wait out a full up-to-max_lanes
    // dispatch and outgrow the host scalar slot).  Express frames
    // coalesce among THEMSELVES (window-free coalescing); bulk frames
    // ride the next take — with multiple pump threads, usually a
    // concurrent one.  NO_BATCHING callers opting out of batching pay
    // their own dispatch, the reference's semantics.
    bool express_take = !b->xq.empty();
    std::deque<IngressFrame*>& src = express_take ? b->xq : b->q;
    while (!src.empty()) {
      IngressFrame* f = src.front();
      if (!tb->frames.empty() && tb->n + f->n > max_lanes) break;
      src.pop_front();
      b->pending_lanes -= f->n;
      tb->n += f->n;
      tb->frames.push_back(f);
    }
    ++b->batches;
  }
  int64_t n = tb->n;
  tb->algo.resize((size_t)n);
  tb->beh.resize((size_t)n);
  tb->hits.resize((size_t)n);
  tb->limit.resize((size_t)n);
  tb->dur.resize((size_t)n);
  tb->hkoff.resize((size_t)n + 1);
  tb->name_off.resize((size_t)n + 1);
  tb->uk_off.resize((size_t)n + 1);
  tb->hashes.resize((size_t)n);
  tb->frame_lanes.resize(tb->frames.size());
  tb->frame_age_us.resize(tb->frames.size());
  auto now = std::chrono::steady_clock::now();
  int64_t lo = 0;
  tb->hkoff[0] = tb->name_off[0] = tb->uk_off[0] = 0;
  for (size_t fi = 0; fi < tb->frames.size(); ++fi) {
    IngressFrame* f = tb->frames[fi];
    int64_t m = f->n;
    const char* body = f->body.data();
    memcpy(tb->algo.data() + lo, body + f->info.algo_pos, (size_t)m * 4);
    memcpy(tb->beh.data() + lo, body + f->info.beh_pos, (size_t)m * 4);
    memcpy(tb->hits.data() + lo, body + f->info.hits_pos, (size_t)m * 8);
    memcpy(tb->limit.data() + lo, body + f->info.limit_pos, (size_t)m * 8);
    memcpy(tb->dur.data() + lo, body + f->info.dur_pos, (size_t)m * 8);
    memcpy(tb->hashes.data() + lo, f->hashes.data(), (size_t)m * 8);
    int64_t hk_base = (int64_t)tb->hk.size();
    tb->hk += f->hk;
    for (int64_t i = 0; i < m; ++i)
      tb->hkoff[(size_t)(lo + i) + 1] = hk_base + f->hkoff[(size_t)i + 1];
    const char* noff = body + f->info.name_off_pos;
    const char* uoff = body + f->info.uk_off_pos;
    int64_t nb_base = (int64_t)tb->name_blob.size();
    int64_t ub_base = (int64_t)tb->uk_blob.size();
    tb->name_blob.append(body + f->info.name_blob_pos, frame_u32(noff + 4 * m));
    tb->uk_blob.append(body + f->info.uk_blob_pos, frame_u32(uoff + 4 * m));
    for (int64_t i = 0; i < m; ++i) {
      tb->name_off[(size_t)(lo + i) + 1] =
          nb_base + (int64_t)frame_u32(noff + 4 * (i + 1));
      tb->uk_off[(size_t)(lo + i) + 1] =
          ub_base + (int64_t)frame_u32(uoff + 4 * (i + 1));
    }
    tb->frame_lanes[fi] = m;
    tb->frame_age_us[fi] =
        (int64_t)std::chrono::duration_cast<std::chrono::microseconds>(
            now - f->arrival)
            .count();
    tb->parse_ns_total += f->parse_ns;
    lo += m;
  }
  out->n = n;
  out->n_frames = (int64_t)tb->frames.size();
  out->algo = tb->algo.data();
  out->beh = tb->beh.data();
  out->hits = tb->hits.data();
  out->limit = tb->limit.data();
  out->duration = tb->dur.data();
  out->hk = tb->hk.data();
  out->hkoff = tb->hkoff.data();
  out->hk_bytes = (int64_t)tb->hk.size();
  out->hashes = tb->hashes.data();
  out->name_blob = tb->name_blob.data();
  out->name_off = tb->name_off.data();
  out->name_bytes = (int64_t)tb->name_blob.size();
  out->uk_blob = tb->uk_blob.data();
  out->uk_off = tb->uk_off.data();
  out->uk_bytes = (int64_t)tb->uk_blob.size();
  out->frame_lanes = tb->frame_lanes.data();
  out->frame_age_us = tb->frame_age_us.data();
  out->parse_ns_total = tb->parse_ns_total;
  *out_tb = tb.release();
  return 1;
}

// Response fill: slice the result arrays per frame, encode each kind-6
// frame (byte-identical to wire.encode_ingress_result_frame with no
// overrides and no owner columns — the fast lane's invariant), wrap in
// the HTTP envelope gt_http_respond emits, and stage on the owning
// acceptor.  One call per batch; releases the handle.
void gt_ingress_complete(void* tbv, const int32_t* status,
                         const int64_t* limit, const int64_t* remaining,
                         const int64_t* reset) {
  auto* tb = (TakenBatch*)tbv;
  int64_t lo = 0;
  for (IngressFrame* f : tb->frames) {
    int64_t m = f->n;
    size_t flen = 10 + (size_t)m * (4 + 8 + 8 + 8) + 8;
    std::string frame;
    frame.reserve(flen);
    frame.append("GUBC", 4);
    uint8_t vk[2] = {1, 6};
    frame.append((const char*)vk, 2);
    uint32_t m32 = (uint32_t)m;
    frame.append((const char*)&m32, 4);
    frame.append((const char*)(status + lo), (size_t)m * 4);
    frame.append((const char*)(limit + lo), (size_t)m * 8);
    frame.append((const char*)(remaining + lo), (size_t)m * 8);
    frame.append((const char*)(reset + lo), (size_t)m * 8);
    uint32_t zero = 0;
    frame.append((const char*)&zero, 4);  // n_owner_addrs = 0
    frame.append((const char*)&zero, 4);  // n_overrides = 0
    std::string resp =
        http_envelope(200, "OK", "application/x-gubernator-columns",
                      frame.data(), (int64_t)frame.size());
    http_stage_response(f->srv, f->token, std::move(resp));
    lo += m;
    ingress_free_frame(f);
  }
  tb->frames.clear();
  delete tb;
}

// Error fill (dispatch failure): every frame of the batch answers the
// same triplet the Python error path would emit.  Releases the handle.
void gt_ingress_fail(void* tbv, int status, const char* reason,
                     const char* ctype, const char* body, int64_t blen) {
  auto* tb = (TakenBatch*)tbv;
  std::string resp = http_envelope(status, reason && *reason ? reason : "Error",
                                   ctype, body, blen);
  for (IngressFrame* f : tb->frames) {
    http_stage_response(f->srv, f->token, std::string(resp));
    ingress_free_frame(f);
  }
  tb->frames.clear();
  delete tb;
}

// Stop: wake the pump (take returns -1 once drained) and answer every
// still-queued frame 503, the worker loop's shutdown wording.
void gt_ingress_stop(void* bv) {
  auto* b = (IngressBatcher*)bv;
  std::deque<IngressFrame*> q;
  {
    std::lock_guard<std::mutex> lk(b->mu);
    b->stopping = true;
    b->enabled = false;
    q.swap(b->q);
    for (IngressFrame* f : b->xq) q.push_back(f);
    b->xq.clear();
    b->pending_lanes = 0;
  }
  b->cv.notify_all();
  const char* msg = "{\"code\": 14, \"message\": \"shutting down\"}";
  std::string resp = http_envelope(503, "Error", "application/json", msg,
                                   (int64_t)strlen(msg));
  for (IngressFrame* f : q) {
    http_stage_response(f->srv, f->token, std::string(resp));
    ingress_free_frame(f);
  }
}

// out: i64[10] = {frames, lanes, batches, shed_frames, shed_lanes,
// fallbacks, pending_frames, pending_lanes, express_frames,
// express_lanes}.  Cumulative; the Python scrape keeps last-seen
// values and feeds deltas into the prometheus counters.
void gt_ingress_stats(void* bv, int64_t* out) {
  auto* b = (IngressBatcher*)bv;
  std::lock_guard<std::mutex> lk(b->mu);
  out[0] = b->frames;
  out[1] = b->lanes;
  out[2] = b->batches;
  out[3] = b->shed_frames;
  out[4] = b->shed_lanes;
  out[5] = b->fallbacks;
  out[6] = (int64_t)(b->q.size() + b->xq.size());
  out[7] = b->pending_lanes;
  out[8] = b->express_frames;
  out[9] = b->express_lanes;
}

void gt_ingress_free(void* bv) {
  auto* b = (IngressBatcher*)bv;
  for (IngressFrame* f : b->q) ingress_free_frame(f);
  for (IngressFrame* f : b->xq) ingress_free_frame(f);
  delete b;
}

}  // extern "C"
