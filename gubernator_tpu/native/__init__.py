"""ctypes loader for the C++ host runtime (host_runtime.cpp).

Compiles the shared library on first import with g++ (cached next to
the source, rebuilt when the source hash changes) and wraps it in
Python classes with the same interface as the pure-Python twins
(models/slot_table.py).  If no compiler is available the package
falls back to the Python implementation — `available()` reports which
path is active.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "host_runtime.cpp")
_LIB_TMPL = os.path.join(_HERE, "_host_runtime_{digest}.so")

_lib = None
_lib_err: Optional[str] = None
_build_lock = threading.Lock()

# THE compile flags, pinned in one place: `make native`, the on-import
# rebuild and the tier-1 source-hash check all go through here, so a
# flag tweak cannot fork a differently-built .so from the one the
# hash-suffix discipline vouches for.
CXX = "g++"
CXX_FLAGS = ["-O2", "-std=c++17", "-shared", "-fPIC"]


def source_digest() -> str:
    """First 16 hex chars of sha256(host_runtime.cpp) — the .so name
    suffix (`_host_runtime_<digest>.so`).  A checked-in binary whose
    suffix does not match the current source is stale by definition
    (tests/test_native_build.py enforces this in tier-1)."""
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def lib_path() -> str:
    """Path the current source compiles to (exists or not)."""
    return _LIB_TMPL.format(digest=source_digest())


def build() -> str:
    """Compile the runtime for the current source if its .so is absent
    (the `make native` entry point); returns the .so path."""
    path = lib_path()
    if not os.path.exists(path):
        err = _compile(path)
        if err is not None:
            raise RuntimeError(err)
    return path


def _compile(lib_path: str) -> Optional[str]:
    """Compile the runtime to lib_path via unique-tmp + rename; returns
    an error string or None."""
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    cmd = [CXX, *CXX_FLAGS, _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return None
    except (OSError, subprocess.SubprocessError) as e:
        return f"native build failed: {e}"


def _build() -> Optional[ctypes.CDLL]:
    global _lib_err
    lib_path = _LIB_TMPL.format(digest=source_digest())
    if os.path.exists(lib_path):
        # Refresh mtime: the stale-prune below is age-based, and an
        # old-mtime .so being REUSED by this process must not look
        # prunable to a concurrently starting process (TOCTOU between
        # our exists() and CDLL()).
        try:
            os.utime(lib_path)
        except OSError:
            pass
    else:
        err = _compile(lib_path)
        if err is not None:
            _lib_err = err
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        # TOCTOU: between our exists()/utime() and the CDLL, another
        # process's age-based prune may have deleted an old .so.  The
        # compile is cheap and writes via unique-tmp + rename, so retry
        # once through the build path instead of falling back to the
        # slow Python slot table for this process's whole lifetime.
        err = _compile(lib_path)
        if err is not None:
            _lib_err = err
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError as e:
            _lib_err = f"native load failed: {e}"
            return None

    # Prune superseded builds: each source edit leaves a hash-named .so
    # behind, which otherwise accumulates without bound.  Only delete
    # files comfortably older than any concurrently-starting process's
    # build window — a racing starter with a different source digest
    # must not lose its fresh .so between write and dlopen.
    import glob
    import time

    cutoff = time.time() - 600
    for stale in glob.glob(_LIB_TMPL.format(digest="*")):
        if stale != lib_path:
            try:
                if os.path.getmtime(stale) < cutoff:
                    os.remove(stale)
            except OSError:
                pass

    c = ctypes
    lib.gt_table_new.restype = c.c_void_p
    lib.gt_table_new.argtypes = [c.c_int64]
    lib.gt_table_free.argtypes = [c.c_void_p]
    lib.gt_table_len.restype = c.c_int64
    lib.gt_table_len.argtypes = [c.c_void_p]
    lib.gt_table_stats.argtypes = [c.c_void_p, c.POINTER(c.c_int64)]
    lib.gt_table_evictions.restype = c.c_int64
    lib.gt_table_evictions.argtypes = [c.c_void_p]
    lib.gt_table_generation.restype = c.c_uint64
    lib.gt_table_generation.argtypes = [c.c_void_p]
    lib.gt_table_get_slot.restype = c.c_int32
    lib.gt_table_get_slot.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.gt_table_lookup_or_assign.argtypes = [
        c.c_void_p, c.c_char_p, c.c_int64, c.c_int64,
        c.POINTER(c.c_int32), c.POINTER(c.c_uint8),
    ]
    lib.gt_table_remove.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.gt_table_set_expire.argtypes = [c.c_void_p, c.c_int32, c.c_int64]
    lib.gt_table_get_expire.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_void_p]
    lib.gt_table_commit.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64]
    lib.gt_table_commit_keys.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64,
    ]
    lib.gt_table_keys_size.argtypes = [c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.c_int64)]
    lib.gt_table_keys.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p]
    lib.gt_batch_begin.restype = c.c_void_p
    lib.gt_batch_begin.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64, c.c_int64]
    lib.gt_batch_next_round.restype = c.c_int64
    lib.gt_batch_next_round.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p]
    lib.gt_batch_commit_round.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
    lib.gt_batch_plan.restype = c.c_int64
    lib.gt_batch_plan.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p]
    lib.gt_batch_plan_grouped.restype = c.c_int64
    lib.gt_batch_plan_grouped.argtypes = [
        c.c_void_p,  # batch
        c.c_void_p, c.c_void_p,  # algo, behavior
        c.c_void_p, c.c_void_p, c.c_void_p,  # hits, limit, duration
        c.c_void_p, c.c_void_p,  # greg_expire, greg_duration
        c.c_int32,  # RESET_REMAINING mask
        c.c_void_p, c.c_void_p, c.c_void_p,  # round_id, slots, exists
        c.c_void_p, c.c_void_p,  # occ, write
    ]
    lib.gt_batch_commit_plan.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
    lib.gt_batch_free.argtypes = [c.c_void_p]
    lib.gt_mesh_begin.restype = c.c_void_p
    lib.gt_mesh_begin.argtypes = [
        c.c_void_p, c.c_int64,  # tables[S], S
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int64,  # keys, offsets, n, now
        c.c_void_p,  # counts[S] out
    ]
    lib.gt_mesh_plan_grouped.restype = c.c_int64
    lib.gt_mesh_plan_grouped.argtypes = [
        c.c_void_p,  # mesh plan
        c.c_void_p, c.c_void_p,  # algo, behavior
        c.c_void_p, c.c_void_p, c.c_void_p,  # hits, limit, duration
        c.c_void_p, c.c_void_p,  # greg_expire, greg_duration
        c.c_int32, c.c_int64,  # reset mask, P
        c.c_void_p, c.c_void_p, c.c_void_p,  # slot, rid, exists
        c.c_void_p, c.c_void_p, c.c_void_p,  # occ, write, pos
    ]
    lib.gt_mesh_finish_narrow.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64,
        c.c_void_p, c.c_void_p, c.c_void_p,
    ]
    lib.gt_mesh_finish_wide.argtypes = [
        c.c_void_p, c.c_void_p,
        c.c_void_p, c.c_void_p, c.c_void_p,
    ]
    lib.gt_mesh_free.argtypes = [c.c_void_p]
    lib.gt_table_enable_back.argtypes = [c.c_void_p, c.c_int64]
    lib.gt_table_tier_stats.argtypes = [c.c_void_p, c.c_void_p]
    lib.gt_table_move_counts.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.c_int64),
    ]
    lib.gt_table_take_moves.argtypes = [c.c_void_p] + [c.c_void_p] * 5
    lib.gt_table_back_size.argtypes = [
        c.c_void_p, c.POINTER(c.c_int64), c.POINTER(c.c_int64),
    ]
    lib.gt_table_back_keys.argtypes = [c.c_void_p] + [c.c_void_p] * 4
    lib.gt_fnv1_batch.argtypes = [c.c_void_p, c.c_void_p, c.c_int64, c.c_int32, c.c_void_p]
    lib.gt_json_parse.restype = c.c_void_p
    lib.gt_json_parse.argtypes = [c.c_char_p, c.c_int64]
    lib.gt_json_n.restype = c.c_int64
    lib.gt_json_n.argtypes = [c.c_void_p]
    lib.gt_json_hk_bytes.restype = c.c_int64
    lib.gt_json_hk_bytes.argtypes = [c.c_void_p]
    lib.gt_json_fill.argtypes = [c.c_void_p] + [c.c_void_p] * 10
    lib.gt_json_free.argtypes = [c.c_void_p]
    lib.gt_json_render.restype = c.c_int64
    lib.gt_json_render.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64,
        c.c_void_p, c.c_int64, c.c_char_p, c.c_void_p, c.c_char_p,
        c.c_int64,
    ]
    lib.gt_frame_parse.restype = c.c_void_p
    lib.gt_frame_parse.argtypes = [
        c.c_char_p, c.c_int64, c.c_int32, c.c_void_p,
    ]
    lib.gt_frame_fill.argtypes = [c.c_void_p] + [c.c_void_p] * 3
    lib.gt_frame_free.argtypes = [c.c_void_p]
    lib.gt_http_start.restype = c.c_void_p
    lib.gt_http_start.argtypes = [c.c_char_p, c.c_int, c.c_int, c.c_char_p]
    lib.gt_http_port.restype = c.c_int
    lib.gt_http_port.argtypes = [c.c_void_p]
    lib.gt_http_acceptor_count.restype = c.c_int
    lib.gt_http_acceptor_count.argtypes = [c.c_void_p]
    lib.gt_http_acceptor_stats.argtypes = [c.c_void_p, c.c_void_p]
    lib.gt_http_next.restype = c.c_int
    lib.gt_http_next.argtypes = [c.c_void_p, c.c_int64, c.c_void_p]
    lib.gt_http_respond.argtypes = [
        c.c_void_p, c.c_uint64, c.c_int, c.c_char_p, c.c_char_p,
        c.c_char_p, c.c_int64,
    ]
    lib.gt_http_shutdown.argtypes = [c.c_void_p]
    lib.gt_http_free.argtypes = [c.c_void_p]
    lib.gt_ingress_new.restype = c.c_void_p
    lib.gt_ingress_new.argtypes = []
    lib.gt_ingress_set_ring.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64,  # vh, vself, nv
        c.c_int32, c.c_int32,                           # all_self, enabled
        c.c_int64, c.c_int64,                # cap_lanes, max_frame_lanes
        c.c_int32, c.c_int32,                # behavior_mask, hash_variant
        c.c_int32,                           # express_mask
    ]
    lib.gt_ingress_submit.restype = c.c_int
    lib.gt_ingress_submit.argtypes = [c.c_void_p, c.c_void_p, c.c_uint64]
    lib.gt_ingress_take.restype = c.c_int
    lib.gt_ingress_take.argtypes = [
        c.c_void_p, c.c_int64, c.c_int64,
        c.POINTER(c.c_void_p), c.c_void_p,
    ]
    lib.gt_ingress_complete.argtypes = [c.c_void_p] + [c.c_void_p] * 4
    lib.gt_ingress_fail.argtypes = [
        c.c_void_p, c.c_int, c.c_char_p, c.c_char_p, c.c_char_p, c.c_int64,
    ]
    lib.gt_ingress_stop.argtypes = [c.c_void_p]
    lib.gt_ingress_stats.argtypes = [c.c_void_p, c.c_void_p]
    lib.gt_ingress_free.argtypes = [c.c_void_p]
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is None and _lib_err is None:
        with _build_lock:
            if _lib is None and _lib_err is None:
                _lib = _build()
    return _lib


def available() -> bool:
    return _get_lib() is not None


def build_error() -> Optional[str]:
    _get_lib()
    return _lib_err


def pack_keys(keys) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate utf-8 keys into (bytes buffer, offsets[n+1])."""
    bs = [k.encode("utf-8") if isinstance(k, str) else k for k in keys]
    offsets = np.zeros(len(bs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in bs], out=offsets[1:])
    return np.frombuffer(b"".join(bs), dtype=np.uint8), offsets


class PackedKeys:
    """Hash keys kept in PACKED form (one utf-8 buffer + offsets[n+1])
    end-to-end: the C++ JSON parser emits this, the batch planner
    consumes it, and per-lane Python strings only materialize for the
    rare slow/error lanes — the edge never pays n string objects per
    batch."""

    __slots__ = ("buf", "offsets")

    def __init__(self, buf: np.ndarray, offsets: np.ndarray):
        self.buf = buf
        self.offsets = offsets

    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i: int) -> str:
        o = self.offsets
        return bytes(self.buf[o[i]:o[i + 1]]).decode("utf-8")

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    @staticmethod
    def concat(parts: "List[PackedKeys]") -> "PackedKeys":
        """Concatenate packed key batches without materializing
        strings (the ColumnarBatcher's multi-submission coalesce)."""
        bufs = [p.buf for p in parts]
        offs = [parts[0].offsets]
        base = int(parts[0].offsets[-1])
        for p in parts[1:]:
            offs.append(p.offsets[1:] + base)
            base += int(p.offsets[-1])
        return PackedKeys(np.concatenate(bufs), np.concatenate(offs))

    def subset(self, idx) -> "PackedKeys":
        """Vectorized selection of lanes `idx` (no per-lane Python)."""
        idx = np.asarray(idx, dtype=np.int64)
        o = self.offsets
        starts = o[idx]
        lens = o[idx + 1] - starts
        new_off = np.zeros(len(idx) + 1, dtype=np.int64)
        np.cumsum(lens, out=new_off[1:])
        total = int(new_off[-1])
        pos = np.repeat(starts - new_off[:-1], lens) + np.arange(total, dtype=np.int64)
        return PackedKeys(self.buf[pos], new_off)


def as_packed(keys) -> Tuple[np.ndarray, np.ndarray]:
    """(buf, offsets) for either a PackedKeys or a list of strings."""
    if isinstance(keys, PackedKeys):
        return keys.buf, keys.offsets
    return pack_keys(keys)


class ParsedJson:
    """Result of the native GetRateLimits JSON parse (gt_json_parse):
    kernel-ready columns + packed hash keys + validation codes +
    (offset, len) spans of each name/unique_key in the body."""

    __slots__ = ("n", "algo", "behavior", "hits", "limit", "duration",
                 "err", "hash_keys", "nspan", "ukspan", "body")

    def __init__(self, n, algo, behavior, hits, limit, duration, err,
                 hash_keys, nspan, ukspan, body):
        self.n = n
        self.algo = algo
        self.behavior = behavior
        self.hits = hits
        self.limit = limit
        self.duration = duration
        self.err = err
        self.hash_keys = hash_keys
        self.nspan = nspan
        self.ukspan = ukspan
        self.body = body

    def name_at(self, i: int) -> str:
        off, ln = self.nspan[2 * i], self.nspan[2 * i + 1]
        return self.body[off:off + ln].decode("utf-8")

    def unique_key_at(self, i: int) -> str:
        off, ln = self.ukspan[2 * i], self.ukspan[2 * i + 1]
        return self.body[off:off + ln].decode("utf-8")


def parse_json_batch(body: bytes) -> Optional[ParsedJson]:
    """Parse a /v1/GetRateLimits body natively; None means "use the
    Python fallback" (escape sequences in keys, floats, behavior flag
    lists, malformed JSON — anything beyond the common wire shape)."""
    lib = _get_lib()
    if lib is None:
        return None
    h = lib.gt_json_parse(body, len(body))
    if not h:
        return None
    try:
        n = int(lib.gt_json_n(h))
        hkb = int(lib.gt_json_hk_bytes(h))
        algo = np.empty(n, dtype=np.int32)
        behavior = np.empty(n, dtype=np.int32)
        hits = np.empty(n, dtype=np.int64)
        limit = np.empty(n, dtype=np.int64)
        duration = np.empty(n, dtype=np.int64)
        err = np.empty(n, dtype=np.uint8)
        hk = np.empty(hkb, dtype=np.uint8)
        hkoff = np.empty(n + 1, dtype=np.int64)
        nspan = np.empty(2 * n, dtype=np.int64)
        ukspan = np.empty(2 * n, dtype=np.int64)
        lib.gt_json_fill(
            h, algo.ctypes.data, behavior.ctypes.data, hits.ctypes.data,
            limit.ctypes.data, duration.ctypes.data, err.ctypes.data,
            hk.ctypes.data, hkoff.ctypes.data, nspan.ctypes.data,
            ukspan.ctypes.data,
        )
    finally:
        lib.gt_json_free(h)
    return ParsedJson(n, algo, behavior, hits, limit, duration, err,
                      PackedKeys(hk, hkoff), nspan, ukspan, body)


class _GtFrameInfo(ctypes.Structure):
    _fields_ = [(name, ctypes.c_int64) for name in (
        "n", "name_off_pos", "name_blob_pos", "uk_off_pos", "uk_blob_pos",
        "algo_pos", "beh_pos", "hits_pos", "limit_pos", "dur_pos",
        "trace_pos", "trace_count", "hk_bytes",
    )]


_INGRESS_FRAME_KIND = 5  # wire._FRAME_KIND_INGRESS_REQ


def parse_ingress_frame(raw: bytes):
    """Parse a public GUBC ingress frame (kind 5) natively: one
    GIL-released pass validates the frame, slices every column (numpy
    views of `raw`, zero-copy numerics), builds the packed hash keys
    and stamps per-lane validation codes — the wire.decode_ingress_frame
    fast path.  None means "use the Python decode" (no native runtime,
    or a malformed frame whose exact error wording the Python path
    owns)."""
    lib = _get_lib()
    if lib is None:
        return None
    info = _GtFrameInfo()
    h = lib.gt_frame_parse(raw, len(raw), _INGRESS_FRAME_KIND,
                           ctypes.byref(info))
    if not h:
        return None
    try:
        n = int(info.n)
        hk = np.empty(max(int(info.hk_bytes), 1), dtype=np.uint8)
        hkoff = np.empty(n + 1, dtype=np.int64)
        err = np.empty(max(n, 1), dtype=np.uint8)
        lib.gt_frame_fill(h, hk.ctypes.data, hkoff.ctypes.data,
                          err.ctypes.data)
    finally:
        lib.gt_frame_free(h)
    from .. import wire  # deferred: wire imports this package lazily

    no = np.frombuffer(raw, np.uint32, n + 1, int(info.name_off_pos))
    uo = np.frombuffer(raw, np.uint32, n + 1, int(info.uk_off_pos))
    nb = raw[int(info.name_blob_pos):int(info.name_blob_pos) + int(no[-1] if n else 0)]
    ub = raw[int(info.uk_blob_pos):int(info.uk_blob_pos) + int(uo[-1] if n else 0)]
    try:
        # Untrusted-edge parity with wire._check_utf8_blobs: invalid
        # UTF-8 must 400 here, not 500 later inside a slow-lane decode.
        nb.decode("utf-8")
        ub.decode("utf-8")
    except UnicodeDecodeError:
        return None  # the Python decode owns the exact error wording
    trace_ctx = None
    if info.trace_count > 0:
        trace_ctx, _ = wire.unpack_trace_entries(raw, int(info.trace_pos))
    return wire.FrameIngressColumns(
        n, nb, no, ub, uo,
        np.frombuffer(raw, np.int32, n, int(info.algo_pos)),
        np.frombuffer(raw, np.int32, n, int(info.beh_pos)),
        np.frombuffer(raw, np.int64, n, int(info.hits_pos)),
        np.frombuffer(raw, np.int64, n, int(info.limit_pos)),
        np.frombuffer(raw, np.int64, n, int(info.dur_pos)),
        trace_ctx=trace_ctx,
        err=err[:n],
        packed=PackedKeys(hk[:int(info.hk_bytes)], hkoff),
    )


def render_json(status, limit, remaining, reset, overrides: dict) -> Optional[bytes]:
    """Build the GetRateLimits response body natively; `overrides` maps
    lane index -> pre-rendered JSON bytes (error / forwarded lanes).
    None when the native runtime is unavailable."""
    lib = _get_lib()
    if lib is None:
        return None
    n = len(status)
    status = np.ascontiguousarray(status, dtype=np.int32)
    limit = np.ascontiguousarray(limit, dtype=np.int64)
    remaining = np.ascontiguousarray(remaining, dtype=np.int64)
    reset = np.ascontiguousarray(reset, dtype=np.int64)
    if overrides:
        items = sorted(overrides.items())
        ov_idx = np.asarray([i for i, _ in items], dtype=np.int64)
        bufs = [b for _, b in items]
        ov_off = np.zeros(len(bufs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in bufs], out=ov_off[1:])
        ov_buf = b"".join(bufs)
    else:
        ov_idx = np.empty(0, dtype=np.int64)
        ov_off = np.zeros(1, dtype=np.int64)
        ov_buf = b""
    n_ov = len(ov_idx)
    # Single-pass render into a worst-case buffer (<=129 bytes per
    # plain lane; see gt_json_render).
    cap = 32 + n * 160 + len(ov_buf) + n_ov * 2
    out = ctypes.create_string_buffer(cap)
    size = lib.gt_json_render(
        status.ctypes.data, limit.ctypes.data, remaining.ctypes.data,
        reset.ctypes.data, n, ov_idx.ctypes.data, n_ov, ov_buf,
        ov_off.ctypes.data, out, cap,
    )
    if size < 0:
        return None  # cap overflow (cannot happen by construction)
    return out.raw[:size]


def fnv1_batch(keys, variant_1a: bool = False) -> np.ndarray:
    """Batch FNV-1/1a 64 hash (replicated_hash.go:31); pure-Python
    fallback when the native build is unavailable."""
    lib = _get_lib()
    out = np.empty(len(keys), dtype=np.uint64)
    if len(keys) == 0:
        return out
    if lib is None:
        from ..utils import hashing

        fn = hashing.fnv1a_64 if variant_1a else hashing.fnv1_64
        for i, k in enumerate(keys):
            out[i] = fn(k.encode("utf-8") if isinstance(k, str) else k)
        return out
    buf, offsets = as_packed(keys)
    lib.gt_fnv1_batch(
        buf.ctypes.data, offsets.ctypes.data, len(keys),
        1 if variant_1a else 0, out.ctypes.data,
    )
    return out


class NativeSlotTable:
    """Drop-in for models.slot_table.SlotTable backed by the C++ table.

    Same semantics: strict expiry (cache.go:151), same-slot recycling on
    expiry (cache.go:138-163), LRU eviction at capacity (cache.go:115-130).
    """

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(_lib_err or "native runtime unavailable")
        self._lib = lib
        self.capacity = capacity
        self._ptr = lib.gt_table_new(capacity)

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.gt_table_free(ptr)
            self._ptr = None

    def __len__(self) -> int:
        return int(self._lib.gt_table_len(self._ptr))

    # -- stats (hit/miss/eviction counters for metrics parity) ---------
    @property
    def _stats(self):
        out = (ctypes.c_int64 * 3)()
        self._lib.gt_table_stats(self._ptr, out)
        return int(out[0]), int(out[1]), int(out[2])

    @property
    def hits(self) -> int:
        return self._stats[0]

    @property
    def misses(self) -> int:
        return self._stats[1]

    @property
    def generation(self) -> int:
        """Key->slot mapping-change counter (Table::map_generation);
        unchanged across two reads == no mapping changed between them."""
        return int(self._lib.gt_table_generation(self._ptr))

    @property
    def evictions(self) -> int:
        # Hot: plan_grouped_python reads this around every lookup, so
        # it takes the single-counter FFI call, not the stats marshal.
        return int(self._lib.gt_table_evictions(self._ptr))

    # ------------------------------------------------------------------
    def get_slot(self, key: str) -> Optional[int]:
        b = key.encode("utf-8")
        s = self._lib.gt_table_get_slot(self._ptr, b, len(b))
        return None if s < 0 else int(s)

    def lookup_or_assign(self, key: str, now_ms: int) -> Tuple[int, bool]:
        b = key.encode("utf-8")
        slot = ctypes.c_int32()
        exists = ctypes.c_uint8()
        self._lib.gt_table_lookup_or_assign(
            self._ptr, b, len(b), now_ms, ctypes.byref(slot), ctypes.byref(exists)
        )
        return int(slot.value), bool(exists.value)

    def remove(self, key: str) -> None:
        b = key.encode("utf-8")
        self._lib.gt_table_remove(self._ptr, b, len(b))

    def set_expire(self, slot: int, expire_ms: int) -> None:
        self._lib.gt_table_set_expire(self._ptr, slot, expire_ms)

    def get_expire_bulk(self, slots) -> np.ndarray:
        """Expiry bookkeeping for many slots at once (narrow-wire
        keep-sentinel decode, ops/buckets.py unpack_output32)."""
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        out = np.empty(max(len(slots), 1), dtype=np.int64)
        self._lib.gt_table_get_expire(
            self._ptr, slots.ctypes.data, len(slots), out.ctypes.data
        )
        return out[: len(slots)]

    def commit(self, slots, new_expire_ms, removed, keys=None) -> None:
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        expire = np.ascontiguousarray(new_expire_ms, dtype=np.int64)
        rm = np.ascontiguousarray(removed, dtype=np.uint8)
        if keys is not None:
            # Staleness-guarded commit (slot_table.py::commit keys check).
            buf, offsets = pack_keys(keys)
            self._lib.gt_table_commit_keys(
                self._ptr, slots.ctypes.data, expire.ctypes.data, rm.ctypes.data,
                buf.ctypes.data if len(buf) else None, offsets.ctypes.data, len(slots),
            )
            return
        self._lib.gt_table_commit(
            self._ptr, slots.ctypes.data, expire.ctypes.data, rm.ctypes.data, len(slots)
        )

    # -- two-tier back tier (front/back split, Table two-tier mode) ----
    def enable_back(self, back_capacity: int) -> None:
        """Turn on the back tier: front LRU evictions demote rows to a
        FIFO back table instead of dropping them; lookups promote them
        back.  Device moves queue in the table until take_moves."""
        self._lib.gt_table_enable_back(self._ptr, back_capacity)

    @property
    def tier_stats(self):
        """(total_keys, back_keys, demotions, promotions, back_evictions)."""
        out = (ctypes.c_int64 * 5)()
        self._lib.gt_table_tier_stats(self._ptr, out)
        return tuple(int(x) for x in out)

    def move_counts(self):
        np_, nd = ctypes.c_int64(), ctypes.c_int64()
        self._lib.gt_table_move_counts(
            self._ptr, ctypes.byref(np_), ctypes.byref(nd)
        )
        return int(np_.value), int(nd.value)

    def take_moves(self):
        """Drain the queued device moves: (promo_kind, promo_src,
        promo_dst, demo_src, demo_dst) i32 arrays.  The caller MUST
        apply them (ops/buckets.apply_moves) before any other device
        program touches the front rows."""
        n_promo, n_demo = self.move_counts()
        pk = np.empty(max(n_promo, 1), dtype=np.int32)
        ps = np.empty(max(n_promo, 1), dtype=np.int32)
        pd = np.empty(max(n_promo, 1), dtype=np.int32)
        ds = np.empty(max(n_demo, 1), dtype=np.int32)
        dd = np.empty(max(n_demo, 1), dtype=np.int32)
        self._lib.gt_table_take_moves(
            self._ptr, pk.ctypes.data, ps.ctypes.data, pd.ctypes.data,
            ds.ctypes.data, dd.ctypes.data,
        )
        return (pk[:n_promo], ps[:n_promo], pd[:n_promo],
                ds[:n_demo], dd[:n_demo])

    def back_entries(self):
        """(keys, back_slots i32, expire i64) of every back-tier row."""
        count = ctypes.c_int64()
        total = ctypes.c_int64()
        self._lib.gt_table_back_size(
            self._ptr, ctypes.byref(count), ctypes.byref(total)
        )
        n, nb = int(count.value), int(total.value)
        if n == 0:
            return [], np.empty(0, np.int32), np.empty(0, np.int64)
        slots = np.empty(n, dtype=np.int32)
        expire = np.empty(n, dtype=np.int64)
        offsets = np.empty(n + 1, dtype=np.int64)
        buf = ctypes.create_string_buffer(max(nb, 1))
        self._lib.gt_table_back_keys(
            self._ptr, slots.ctypes.data, expire.ctypes.data,
            offsets.ctypes.data, buf,
        )
        raw = buf.raw[:nb]
        keys = [
            raw[offsets[i]:offsets[i + 1]].decode("utf-8") for i in range(n)
        ]
        return keys, slots, expire

    def keys(self) -> List[str]:
        count = ctypes.c_int64()
        total = ctypes.c_int64()
        self._lib.gt_table_keys_size(self._ptr, ctypes.byref(count), ctypes.byref(total))
        n, nb = int(count.value), int(total.value)
        if n == 0:
            return []
        slots = np.empty(n, dtype=np.int32)
        offsets = np.empty(n + 1, dtype=np.int64)
        buf = ctypes.create_string_buffer(max(nb, 1))
        self._lib.gt_table_keys(self._ptr, slots.ctypes.data, offsets.ctypes.data, buf)
        raw = buf.raw[:nb]
        return [raw[offsets[i]:offsets[i + 1]].decode("utf-8") for i in range(n)]


class NativeBatchPlanner:
    """Round planner over a NativeSlotTable: resolve + split a whole key
    batch into race-free kernel rounds in C++ (shard.py::RoundPlanner).
    """

    def __init__(self, table: NativeSlotTable, keys, now_ms: int):
        self._lib = table._lib
        self._table = table
        self.n = len(keys)
        self._buf, self._offsets = as_packed(keys)
        self._ptr = self._lib.gt_batch_begin(
            table._ptr, self._buf.ctypes.data if self.n else None,
            self._offsets.ctypes.data, self.n, now_ms,
        )
        self._lane = np.empty(max(self.n, 1), dtype=np.int32)
        self._slot = np.empty(max(self.n, 1), dtype=np.int32)
        self._exists = np.empty(max(self.n, 1), dtype=np.uint8)

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.gt_batch_free(ptr)
            self._ptr = None

    def next_round(self):
        """Returns (lane_idx, slots, exists) views for the next round, or
        None when the batch is exhausted."""
        m = self._lib.gt_batch_next_round(
            self._ptr, self._lane.ctypes.data, self._slot.ctypes.data,
            self._exists.ctypes.data,
        )
        if m == 0:
            return None
        return self._lane[:m], self._slot[:m], self._exists[:m].astype(bool)

    def commit_round(self, new_expire_ms, removed) -> None:
        expire = np.ascontiguousarray(new_expire_ms, dtype=np.int64)
        rm = np.ascontiguousarray(removed, dtype=np.uint8)
        self._lib.gt_batch_commit_round(self._ptr, expire.ctypes.data, rm.ctypes.data)

    def plan(self):
        """Plan ALL rounds upfront (no interleaved commits): returns
        (round_id[n] i32, slot[n] i32, exists[n] bool, n_rounds) for the
        single-dispatch kernel path (ops/buckets.py apply_rounds)."""
        round_id = np.empty(max(self.n, 1), dtype=np.int32)
        slots = np.empty(max(self.n, 1), dtype=np.int32)
        exists = np.empty(max(self.n, 1), dtype=np.uint8)
        n_rounds = self._lib.gt_batch_plan(
            self._ptr, round_id.ctypes.data, slots.ctypes.data, exists.ctypes.data
        )
        return (
            round_id[: self.n],
            slots[: self.n],
            exists[: self.n].astype(bool),
            int(n_rounds),
        )

    def plan_grouped(self, cols, reset_mask: int):
        """Grouped full plan (gt_batch_plan_grouped): uniform duplicate
        groups collapse into round 0 with per-lane occurrence indices;
        the rest use rounds 1+.  `cols` carries contiguous algo(i32),
        behavior(i32), hits/limit/duration/greg_expire/greg_duration
        (i64) arrays aligned with the batch keys.  Returns (round_id,
        slot, exists, occ, write, n_rounds)."""
        n = max(self.n, 1)
        round_id = np.zeros(n, dtype=np.int32)
        slots = np.empty(n, dtype=np.int32)
        exists = np.empty(n, dtype=np.uint8)
        occ = np.zeros(n, dtype=np.int32)
        write = np.empty(n, dtype=np.uint8)
        n_rounds = self._lib.gt_batch_plan_grouped(
            self._ptr,
            cols.algo.ctypes.data, cols.behavior.ctypes.data,
            cols.hits.ctypes.data, cols.limit.ctypes.data,
            cols.duration.ctypes.data,
            cols.greg_expire.ctypes.data, cols.greg_duration.ctypes.data,
            reset_mask,
            round_id.ctypes.data, slots.ctypes.data, exists.ctypes.data,
            occ.ctypes.data, write.ctypes.data,
        )
        m = self.n
        return (
            round_id[:m], slots[:m], exists[:m].astype(bool),
            occ[:m], write[:m].astype(bool), int(n_rounds),
        )

    def commit_plan(self, new_expire_ms, removed) -> None:
        """Fold kernel outputs (indexed by ORIGINAL lane order) back into
        the table, last-write-per-key wins."""
        expire = np.ascontiguousarray(new_expire_ms, dtype=np.int64)
        rm = np.ascontiguousarray(removed, dtype=np.uint8)
        self._lib.gt_batch_commit_plan(self._ptr, expire.ctypes.data, rm.ctypes.data)


class NativeMeshPlanner:
    """Whole-mesh columnar planning in single C++ calls: shard-bucket
    (fnv1a % S), per-shard grouped round planning into padded [S, P]
    arrays, and post-dispatch decode + slot-table commit + original-
    order response scatter (gt_mesh_*).  Replaces the round-3 Python
    loop over shards in parallel/mesh.py's columnar dispatch.

    Lifecycle (plan under the store's `_plan_lock`; finish from the
    FIFO resolver — the per-table C++ mutex makes a finish safe
    against the NEXT batch's concurrent plan):
        mp = NativeMeshPlanner(tables, keys, now_ms)   # begin: counts
        plan = mp.plan_grouped(cols, reset_mask)       # padded arrays
        ... device dispatch ...
        status, remaining, reset = mp.finish_narrow(packed_np, now_ms)
    """

    __slots__ = ("_lib", "_tables", "_ptr", "n", "counts", "padded",
                 "pos", "slot", "rid", "exists", "occ", "write",
                 "_keepalive")

    def __init__(self, tables, keys, now_ms: int):
        self._lib = tables[0]._lib
        self._tables = tables  # keep tables (and their C ptrs) alive
        S = len(tables)
        buf, offsets = as_packed(keys)
        self.n = len(offsets) - 1
        self.counts = np.zeros(S, dtype=np.int64)
        ptrs = (ctypes.c_void_p * S)(*[t._ptr for t in tables])
        self._keepalive = (buf, offsets, ptrs)
        self._ptr = self._lib.gt_mesh_begin(
            ptrs, S, buf.ctypes.data if self.n else None,
            offsets.ctypes.data, self.n, now_ms, self.counts.ctypes.data,
        )

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.gt_mesh_free(ptr)
            self._ptr = None

    def plan_grouped(self, cols, reset_mask: int, padded: int):
        """Plan every shard into padded [S, P] row-major arrays; returns
        n_rounds.  Padding lanes keep slot=-1 / zeros."""
        S = len(self.counts)
        self.padded = padded
        self.slot = np.full((S, padded), -1, dtype=np.int32)
        self.rid = np.zeros((S, padded), dtype=np.int32)
        self.exists = np.zeros((S, padded), dtype=np.uint8)
        self.occ = np.zeros((S, padded), dtype=np.int32)
        self.write = np.zeros((S, padded), dtype=np.uint8)
        self.pos = np.zeros(max(self.n, 1), dtype=np.int64)
        n_rounds = self._lib.gt_mesh_plan_grouped(
            self._ptr,
            cols.algo.ctypes.data, cols.behavior.ctypes.data,
            cols.hits.ctypes.data, cols.limit.ctypes.data,
            cols.duration.ctypes.data,
            cols.greg_expire.ctypes.data, cols.greg_duration.ctypes.data,
            reset_mask, padded,
            self.slot.ctypes.data, self.rid.ctypes.data,
            self.exists.ctypes.data, self.occ.ctypes.data,
            self.write.ctypes.data, self.pos.ctypes.data,
        )
        return int(n_rounds)

    def finish_narrow(self, packed_np, now_ms: int):
        """Decode + commit a narrow i32[S, 4, P] result; returns
        (status i32[n], remaining i64[n], reset_time i64[n]) in
        ORIGINAL lane order."""
        packed_np = np.ascontiguousarray(packed_np, dtype=np.int32)
        status = np.empty(max(self.n, 1), dtype=np.int32)
        remaining = np.empty(max(self.n, 1), dtype=np.int64)
        reset = np.empty(max(self.n, 1), dtype=np.int64)
        self._lib.gt_mesh_finish_narrow(
            self._ptr, packed_np.ctypes.data, now_ms,
            status.ctypes.data, remaining.ctypes.data, reset.ctypes.data,
        )
        return status[: self.n], remaining[: self.n], reset[: self.n]

    def finish_wide(self, packed_np):
        """Decode + commit a wide i64[S, 4, P] result (absolute values)."""
        packed_np = np.ascontiguousarray(packed_np, dtype=np.int64)
        status = np.empty(max(self.n, 1), dtype=np.int32)
        remaining = np.empty(max(self.n, 1), dtype=np.int64)
        reset = np.empty(max(self.n, 1), dtype=np.int64)
        self._lib.gt_mesh_finish_wide(
            self._ptr, packed_np.ctypes.data,
            status.ctypes.data, remaining.ctypes.data, reset.ctypes.data,
        )
        return status[: self.n], remaining[: self.n], reset[: self.n]


class _GtHttpReq(ctypes.Structure):
    _fields_ = [
        ("token", ctypes.c_uint64),
        ("method", ctypes.c_int32),
        ("path_len", ctypes.c_int32),
        ("body_len", ctypes.c_int64),
        ("path", ctypes.c_char_p),
        ("body", ctypes.POINTER(ctypes.c_char)),
    ]


_HTTP_METHODS = {0: "GET", 1: "POST"}


#: Sentinel next() returns when the native fast lane consumed the
#: request (gt_ingress_submit took ownership — no Python handling).
FAST_LANE = object()

_INGRESS_SNIFF = b"GUBC\x01\x05"  # magic + version + kind-5


class HttpEdge:
    """ctypes wrapper over the C++ epoll HTTP server (gt_http_*).

    `acceptors` native epoll threads share the TCP port via
    SO_REUSEPORT (1 = the classic single loop); `uds_path` adds an
    AF_UNIX listener speaking the same protocol.  Python workers call
    next() (GIL released while blocked in the native wait) and answer
    with respond().  See gateway.NativeGatewayServer for the worker
    loop."""

    def __init__(self, listen_address: str = "127.0.0.1:0",
                 acceptors: int = 1, uds_path: str = ""):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {build_error()}")
        self._lib = lib
        host, _, port = listen_address.partition(":")
        # gt_http_start takes a dotted-quad (AF_INET): resolve hostnames
        # here so 'localhost:1051' etc. keep working like the stdlib
        # gateway.  IPv6 listen addresses are not supported by this edge.
        import socket as _socket

        host_ip = _socket.gethostbyname(host or "127.0.0.1")
        self._ptr = lib.gt_http_start(
            host_ip.encode(), int(port or 0), int(acceptors),
            uds_path.encode(),
        )
        if not self._ptr:
            raise OSError(
                f"gt_http_start failed to bind {listen_address}"
                + (f" / uds {uds_path}" if uds_path else "")
            )
        self.port = int(lib.gt_http_port(self._ptr))
        self.acceptors = int(lib.gt_http_acceptor_count(self._ptr))
        self.uds_path = uds_path
        self.stopped = False
        self._freed = False
        self._stop_lock = threading.Lock()

    def acceptor_stats(self):
        """Per-acceptor counters: list of dicts {uds, accepted,
        requests, ingressFrames, ingressLanes, wakeups, conns} — the
        gubernator_ingress_acceptor_* metric source and the fairness
        tests' oracle.  A freed edge reads as empty, never a crash."""
        if self._ptr is None:
            return []
        n = self.acceptors
        out = np.zeros(n * 7, dtype=np.int64)
        self._lib.gt_http_acceptor_stats(self._ptr, out.ctypes.data)
        keys = ("uds", "accepted", "requests", "ingressFrames",
                "ingressLanes", "wakeups", "conns")
        return [
            dict(zip(keys, (int(v) for v in out[i * 7:(i + 1) * 7])))
            for i in range(n)
        ]

    def next(self, timeout_ms: int = 200, ingress=None):
        """Blocks up to timeout_ms for one parsed request.  Returns
        (token, method, path, body_bytes), None (timeout/stopping), or
        FAST_LANE when `ingress` (an IngressBatcher) consumed the
        request natively — a POST /v1/GetRateLimits whose body sniffs
        as a kind-5 frame goes through gt_ingress_submit WITHOUT
        copying the body into Python; any fallback reason (malformed,
        slow lanes, remote owners, disabled) falls through to the
        ordinary copy-out so the Python path serves it unchanged.
        The copied body means the token may be answered from any
        thread at any later time."""
        if self.stopped:
            return None
        req = _GtHttpReq()
        rc = self._lib.gt_http_next(self._ptr, timeout_ms, ctypes.byref(req))
        if rc != 1:
            return None
        if (
            ingress is not None
            and req.method == 1
            and req.body_len >= 10
            and ctypes.string_at(req.body, 6) == _INGRESS_SNIFF
            and req.path == b"/v1/GetRateLimits"
        ):
            if self._lib.gt_ingress_submit(
                self._ptr, ingress._ptr, req.token
            ) == 0:
                return FAST_LANE
        method = _HTTP_METHODS.get(req.method, "OTHER")
        path = req.path.decode("utf-8", "replace") if req.path else ""
        body = ctypes.string_at(req.body, req.body_len) if req.body_len else b""
        return req.token, method, path, body

    def respond(self, token: int, status: int, body: bytes,
                reason: str = "OK", content_type: str = "application/json"):
        self._lib.gt_http_respond(
            self._ptr, token, status, reason.encode(), content_type.encode(),
            body, len(body),
        )

    def shutdown(self) -> None:
        """Phase 1: stop traffic (closes sockets, joins the native
        epoll thread).  The HttpServer stays ALLOCATED: workers still
        blocked in next() or about to respond() keep valid memory.
        Callers must join their workers, then call free()."""
        with self._stop_lock:
            if self.stopped:
                return
            self.stopped = True
        self._lib.gt_http_shutdown(self._ptr)

    def free(self) -> None:
        """Phase 2: release the native server.  Only safe after every
        worker thread using this edge has exited."""
        with self._stop_lock:
            if self._freed or self._ptr is None:
                return
            self._freed = True
        self._lib.gt_http_free(self._ptr)
        self._ptr = None


class _GtTakenInfo(ctypes.Structure):
    _fields_ = [
        ("n", ctypes.c_int64),
        ("n_frames", ctypes.c_int64),
        ("algo", ctypes.POINTER(ctypes.c_int32)),
        ("beh", ctypes.POINTER(ctypes.c_int32)),
        ("hits", ctypes.POINTER(ctypes.c_int64)),
        ("limit", ctypes.POINTER(ctypes.c_int64)),
        ("duration", ctypes.POINTER(ctypes.c_int64)),
        ("hk", ctypes.POINTER(ctypes.c_uint8)),
        ("hkoff", ctypes.POINTER(ctypes.c_int64)),
        ("hk_bytes", ctypes.c_int64),
        ("hashes", ctypes.POINTER(ctypes.c_uint64)),
        ("name_blob", ctypes.POINTER(ctypes.c_uint8)),
        ("name_off", ctypes.POINTER(ctypes.c_int64)),
        ("name_bytes", ctypes.c_int64),
        ("uk_blob", ctypes.POINTER(ctypes.c_uint8)),
        ("uk_off", ctypes.POINTER(ctypes.c_int64)),
        ("uk_bytes", ctypes.c_int64),
        ("frame_lanes", ctypes.POINTER(ctypes.c_int64)),
        ("frame_age_us", ctypes.POINTER(ctypes.c_int64)),
        ("parse_ns_total", ctypes.c_int64),
    ]


def _view(ptr, n, dtype):
    """Zero-copy numpy view over a C pointer (no ownership)."""
    if n == 0:
        return np.zeros(0, dtype=dtype)
    return np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
        shape=((n * np.dtype(dtype).itemsize),),
    ).view(dtype)


class IngressTakenBatch:
    """One coalesced batch from the native ingress ring: contiguous
    kernel-ready column arrays spanning every taken frame, as ZERO-COPY
    numpy views of C++-owned buffers.  Valid ONLY until
    IngressBatcher.complete()/fail() releases the handle — the pump is
    the sole owner and must not let views escape the dispatch round.

    Quacks like wire.FrameIngressColumns where the batch-granularity
    folds need it (len, .hits/.behavior/..., `_nb`/`_no`/`_uo` name
    columns for the tenant fold, packed hash keys + ring hashes for
    the hot-key sketch)."""

    __slots__ = ("_ptr", "n", "n_frames", "algorithm", "behavior", "hits",
                 "limit", "duration", "hash_keys", "hashes", "frame_lanes",
                 "frame_age_us", "parse_ns_total", "_nb", "_no", "_ub",
                 "_uo", "trace_ctx")

    def __init__(self, ptr, info: _GtTakenInfo):
        self._ptr = ptr
        n = int(info.n)
        self.n = n
        self.n_frames = int(info.n_frames)
        self.algorithm = _view(info.algo, n, np.int32)
        self.behavior = _view(info.beh, n, np.int32)
        self.hits = _view(info.hits, n, np.int64)
        self.limit = _view(info.limit, n, np.int64)
        self.duration = _view(info.duration, n, np.int64)
        self.hash_keys = PackedKeys(
            _view(info.hk, int(info.hk_bytes), np.uint8),
            _view(info.hkoff, n + 1, np.int64),
        )
        self.hashes = _view(info.hashes, n, np.uint64)
        self._nb = _view(info.name_blob, int(info.name_bytes), np.uint8)
        self._no = _view(info.name_off, n + 1, np.int64)
        self._ub = _view(info.uk_blob, int(info.uk_bytes), np.uint8)
        self._uo = _view(info.uk_off, n + 1, np.int64)
        self.frame_lanes = _view(info.frame_lanes, self.n_frames, np.int64)
        self.frame_age_us = _view(info.frame_age_us, self.n_frames, np.int64)
        self.parse_ns_total = int(info.parse_ns_total)
        self.trace_ctx = None  # fast lane never carries sampled frames

    def __len__(self) -> int:
        return self.n

    def _name_at(self, i: int) -> str:
        return bytes(self._nb[self._no[i]:self._no[i + 1]]).decode("utf-8")

    def _uk_at(self, i: int) -> str:
        return bytes(self._ub[self._uo[i]:self._uo[i + 1]]).decode("utf-8")


class IngressBatcher:
    """The native ingress ring (gt_ingress_*): gateway workers submit
    kind-5 frames GIL-free; the NativeIngressPump takes coalesced
    batches, dispatches them at batch granularity, and completes them
    back into native kind-6 response fills.  See host_runtime.cpp
    'Native ingress service loop' for the full contract."""

    STAT_KEYS = ("frames", "lanes", "batches", "shedFrames", "shedLanes",
                 "fallbacks", "pendingFrames", "pendingLanes",
                 "expressFrames", "expressLanes")

    def __init__(self):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(f"native runtime unavailable: {build_error()}")
        self._lib = lib
        self._ptr = lib.gt_ingress_new()
        self.stopped = False

    def set_ring(self, vnode_hashes, vnode_self, *, all_self: bool,
                 enabled: bool, cap_lanes: int, max_frame_lanes: int,
                 behavior_mask: int, hash_variant: int = 0,
                 express_mask: int = 0) -> None:
        vh = np.ascontiguousarray(vnode_hashes, dtype=np.uint64)
        vs = np.ascontiguousarray(vnode_self, dtype=np.uint8)
        self._lib.gt_ingress_set_ring(
            self._ptr, vh.ctypes.data, vs.ctypes.data, len(vh),
            1 if all_self else 0, 1 if enabled else 0,
            int(cap_lanes), int(max_frame_lanes), int(behavior_mask),
            int(hash_variant), int(express_mask),
        )

    def disable(self) -> None:
        """Fast path off (every submit falls back to Python) without
        touching the rest of the config."""
        self.set_ring(
            np.zeros(0, np.uint64), np.zeros(0, np.uint8),
            all_self=False, enabled=False, cap_lanes=0,
            max_frame_lanes=0, behavior_mask=0,
        )

    def take(self, max_lanes: int, timeout_ms: int = 200):
        """Block (GIL released) for one coalesced batch; None on
        timeout or shutdown (check .stopped)."""
        tb = ctypes.c_void_p()
        info = _GtTakenInfo()
        rc = self._lib.gt_ingress_take(
            self._ptr, int(max_lanes), int(timeout_ms),
            ctypes.byref(tb), ctypes.byref(info),
        )
        if rc == -1:
            self.stopped = True
            return None
        if rc != 1:
            return None
        return IngressTakenBatch(tb, info)

    def complete(self, tb: IngressTakenBatch, status, limit, remaining,
                 reset_time) -> None:
        """Native response fill: per-frame kind-6 encode + write.
        Consumes the handle — the batch's views die here.  A handle
        already consumed is a no-op (an error in post-complete
        bookkeeping must never double-answer or crash)."""
        if tb._ptr is None:
            return
        status = np.ascontiguousarray(status, dtype=np.int32)
        limit = np.ascontiguousarray(limit, dtype=np.int64)
        remaining = np.ascontiguousarray(remaining, dtype=np.int64)
        reset_time = np.ascontiguousarray(reset_time, dtype=np.int64)
        ptr, tb._ptr = tb._ptr, None
        self._lib.gt_ingress_complete(
            ptr, status.ctypes.data, limit.ctypes.data,
            remaining.ctypes.data, reset_time.ctypes.data,
        )

    def fail(self, tb: IngressTakenBatch, status: int, reason: str,
             content_type: str, body: bytes) -> None:
        """Error fill: every frame of the batch answers `body`.
        Consumes the handle; a handle already consumed is a no-op —
        passing a freed batch into the native fill would be a
        use-after-free, and its frames were already answered."""
        if tb._ptr is None:
            return
        ptr, tb._ptr = tb._ptr, None
        self._lib.gt_ingress_fail(
            ptr, int(status), reason.encode(), content_type.encode(),
            body, len(body),
        )

    def stop(self) -> None:
        """Wake the pump and 503 any still-queued frames."""
        self.stopped = True
        self._lib.gt_ingress_stop(self._ptr)

    def stats(self) -> dict:
        out = np.zeros(10, dtype=np.int64)
        if self._ptr:  # freed batchers read as all-zero, never crash
            self._lib.gt_ingress_stats(self._ptr, out.ctypes.data)
        return dict(zip(self.STAT_KEYS, (int(v) for v in out)))

    def free(self) -> None:
        ptr, self._ptr = self._ptr, None
        if ptr:
            self._lib.gt_ingress_free(ptr)
