"""Configuration: behavior knobs, service/daemon config, GUBER_* env parsing.

Mirrors config.go: `BehaviorConfig` (config.go:42-63) with the same
defaults (BatchTimeout 500ms, BatchWait 500us, BatchLimit 1000, and the
GLOBAL/multi-region equivalents, config.go:106-133), `DaemonConfig`
(config.go:155-202), and `setup_daemon_config` env handling
(config.go:220-388): env-file lines -> GUBER_* environment -> defaults.

Divergence: the default GLOBAL/multi-region sync window is 100ms instead
of the reference's 500us — each sync here is a device collective whose
dispatch cost wants amortizing; tests and deployments tune it down
exactly like the reference's own test harness does
(cluster/cluster.go:104-110 uses 50ms).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .types import PeerInfo

MAX_BATCH_SIZE = 1000  # gubernator.go:36

# Lane cap for ONE columnar peer RPC (wire.py "columnar peer hop").
# The reference's 1000-item cap guards the CLIENT surface; the internal
# columnar hop exists to coalesce many concurrent ingress batches into
# one RPC, so it carries more — 16k lanes is ~600KB of frame/proto,
# well under the 1MB gRPC receive cap, and 1/4 of the device's 64k-lane
# dispatch ceiling.  Classic (pre-columns) peers still receive
# MAX_BATCH_SIZE chunks.
PEER_COLUMNS_MAX_LANES = 16_384

# Lane cap for ONE public columnar ingress request (wire.py "public
# columnar ingress").  The reference's 1000-item cap guards the classic
# JSON/pb surface unchanged; a columnar client exists to accumulate
# many callers' checks into one frame, so its cap matches the peer
# hop's — the daemon-side budget arithmetic (ingress queue, device
# ceiling) already accounts for batches this size arriving from peers.
INGRESS_COLUMNS_MAX_LANES = PEER_COLUMNS_MAX_LANES


@dataclass
class BehaviorConfig:
    """config.go:42-63 (durations in seconds)."""

    batch_timeout_s: float = 0.5
    batch_wait_s: float = 0.0005
    batch_limit: int = 1000
    # Bounded ingress queue (lanes): the LocalBatcher/ColumnarBatcher
    # coalescing windows admit at most this many queued LANES (a
    # multi-item columnar submission counts every lane).  A submission
    # that would exceed the cap is SHED with a 429-style
    # ResourceExhausted error (NOT an OVER_LIMIT status — that is an
    # answer about the client's limit, not about daemon overload) and
    # counted in gubernator_ingress_shed_total.  Rationale: the queue
    # was unbounded through BENCH_r05, where an ingress storm stretched
    # service p99 to 4.5s — every queued caller pays the backlog, so
    # past the point where queued work exceeds any useful deadline,
    # shedding is strictly kinder than queueing.  The default admits
    # ~4 full device dispatch ceilings (4 x 64k lanes); 0 disables the
    # bound.  The bound is PER INGRESS LANE: the native service loop's
    # ring (GUBER_NATIVE_INGRESS) and the Python coalescing windows
    # each enforce it on the lanes they queue — mixed fast-lane +
    # fallback traffic can therefore hold up to 2x this many lanes
    # total, still bounded, before both lanes shed.
    # Env: GUBER_INGRESS_QUEUE_LANES.
    ingress_queue_lanes: int = 262_144
    # Columnar peer hop (wire.py "columnar peer hop"): forwarded batches
    # travel as column arrays (proto columns on gRPC, the binary frame
    # on HTTP) and are served from the columnar receive path.  False
    # disables BOTH directions — the daemon neither sends nor serves
    # columns, behaving exactly like a pre-columns peer (the
    # mixed-version interop tests run one daemon in this mode).
    # Env: GUBER_PEER_COLUMNS.
    peer_columns: bool = True
    # Native ingress service loop (host_runtime.cpp gt_ingress_*): on
    # the native HTTP edge, steady-state kind-5 ingress frames are
    # validated, hashed, ring-routed, coalesced, dispatched and
    # answered with Python touching only batch-granularity control —
    # the GIL leaves the per-frame path entirely.  False = the PR 8
    # edge: every frame decodes/encodes through the Python gateway
    # path (behavior-identical — the fast lane serves only semantics
    # the Python path also serves; this knob exists for A/B and as the
    # interop-proof off switch).  Env: GUBER_NATIVE_INGRESS.
    native_ingress: bool = True
    # Public columnar ingress (wire.py "public columnar ingress", the
    # front door): the daemon sniffs GUBC kind-5 frames on
    # POST /v1/GetRateLimits and serves V1/GetRateLimitsColumns over
    # gRPC, decoding client batches straight into ingress columns (no
    # per-request JSON/dict/dataclass work) and answering from the
    # result arrays.  False withholds both surfaces — a columns client
    # sees 400/UNIMPLEMENTED exactly like against a pre-columns build
    # and falls back sticky to classic JSON (the mixed-version interop
    # mode); classic clients are unaffected either way.
    # Env: GUBER_INGRESS_COLUMNS.
    ingress_columns: bool = True

    global_timeout_s: float = 0.5
    # None = AUTO: size the window from the measured device cost of one
    # sync collective (GlobalManager resolves it at startup so the sync
    # overhead stays ~10% of the window).  Set a float (or
    # GUBER_GLOBAL_SYNC_WAIT) to pin it, as the test harness does
    # (cluster.py uses 50ms, mirroring cluster/cluster.go:104-110).
    global_sync_wait_s: Optional[float] = None
    global_batch_limit: int = 1000
    # Columnar GLOBAL replication plane (architecture.md "GLOBAL
    # plane"): broadcasts travel as one GlobalsColumns batch (proto
    # columns on gRPC, the GUBC globals frame on HTTP), encoded once
    # per tick and committed by the receiver in one device program;
    # forwarded GLOBAL hits ride the columnar GetPeerRateLimits path.
    # False disables BOTH directions — the daemon sends per-item
    # classic encodings, serves no columnar globals surface, and
    # commits received broadcasts per item, behaving exactly like a
    # pre-columns peer (wire- and dispatch-identical; the interop mode).
    # Env: GUBER_GLOBAL_COLUMNS.
    global_columns: bool = True
    # Broadcast fan-out concurrency: the GlobalManager sends one
    # sync pass's broadcasts to all peers through a pool of this many
    # workers, so tick wall-time stops scaling as peers x RTT (the
    # pre-columns sender fanned out serially).  Env: GUBER_GLOBAL_FANOUT.
    global_fanout: int = 8

    # -- multi-region federation plane (federation.py) -----------------
    # Per-send deadline of one cross-region batch.
    # Env: GUBER_MULTI_REGION_TIMEOUT.
    multi_region_timeout_s: float = 0.5
    # Flush window of the per-region accumulator: MULTI_REGION hits
    # aggregate per key for this long, then one encode-once batch fans
    # to every remote region's owners.  Env: GUBER_MULTI_REGION_SYNC_WAIT.
    multi_region_sync_wait_s: float = 0.1
    # Queue-full early flush (multiregion.go batching semantics): the
    # accumulator flushes IMMEDIATELY when it holds this many distinct
    # keys instead of waiting out the window.  0 disables the early
    # kick (window-only flushes).  Env: GUBER_MULTI_REGION_BATCH_LIMIT.
    multi_region_batch_limit: int = 1000
    # Columnar inter-region wire (the GUBC region frame / proto
    # RegionColumnsReq served as PeersV1/UpdateRegionColumns).  False
    # disables BOTH directions — sends use the classic per-item
    # GetPeerRateLimits encoding (byte-identical to the pre-federation
    # sender) and the region surface is withheld so peers see
    # UNIMPLEMENTED/404, exactly like a pre-federation daemon (the
    # mixed-version interop mode).  Env: GUBER_REGION_COLUMNS.
    region_columns: bool = True

    # -- peer fault tolerance (faults.py) ------------------------------
    # Per-peer circuit breaker: this many consecutive transport
    # failures open the circuit; while open, calls to the peer fail
    # fast and forwarded keys degrade to local evaluation.  After the
    # open interval one half-open probe decides re-close vs re-open.
    circuit_threshold: int = 5  # GUBER_CIRCUIT_THRESHOLD
    circuit_open_interval_s: float = 2.0  # GUBER_CIRCUIT_OPEN_INTERVAL
    # Forward re-pick loop: attempt budget (the reference hardcodes 5,
    # gubernator.go:154-162) and the jittered-backoff envelope slept
    # between attempts (full jitter, so a herd that saw one peer die
    # does not retry in lockstep).
    forward_retry_limit: int = 5  # GUBER_FORWARD_RETRY_LIMIT
    retry_backoff_base_s: float = 0.02  # GUBER_RETRY_BACKOFF_BASE
    retry_backoff_max_s: float = 1.0  # GUBER_RETRY_BACKOFF_MAX
    # Host-tier GLOBAL / multi-region send loops: retries per peer send
    # per tick (0 = one attempt, no retry).  Kept small — a failed peer
    # is the breaker's job across ticks, not this budget's.
    global_send_retries: int = 1  # GUBER_GLOBAL_SEND_RETRIES

    # -- request tracing (tracing.py) ----------------------------------
    # Ingress sampling rate, 0..1.  0 (the default) disables tracing
    # entirely: every hook is a single comparison and the peer wire is
    # byte-identical to a pre-trace build (the interop parity
    # contract).  The daemon applies this process-wide at startup.
    # Env: GUBER_TRACE_SAMPLE.
    trace_sample: float = 0.0

    # -- millisecond express lane (architecture.md "Express lane") -----
    # Shallow-queue latency bypass: small submissions dispatch
    # IMMEDIATELY (no coalescing window) when the batcher queue and the
    # dispatch pipeline are shallow, singleton checks on CPU backends
    # take the host-side scalar path (ops/scalar.py, zero device
    # programs), NO_BATCHING frames ride the native express queue
    # instead of the Python fallback, and GUBER_LATENCY_TARGET_MS caps
    # the effective coalescing window (see latency_target_ms below).
    # False = exact pre-express behavior: every submission waits out
    # the window, NO_BATCHING frames on the native edge fall back to
    # Python, the window is occupancy-sized only (the interop/A-B off
    # switch; byte-identical results either way — the bypass changes
    # WHEN a dispatch launches, never what it computes).
    # Env: GUBER_EXPRESS.
    express: bool = True
    # Bypass shallow-queue threshold, in queued LANES: a submission
    # takes the express bypass only while fewer than this many lanes
    # are queued at its batcher (deeper queues mean the window is
    # already coalescing real backlog — bypassing it would only add
    # dispatches without helping latency).  Env: GUBER_EXPRESS_QUEUE_DEPTH.
    express_queue_depth: int = 64
    # Bypass small-batch ceiling, in lanes: submissions wider than this
    # always take the window (a wide batch amortizes its own dispatch;
    # the bypass exists for the 1-4 lane interactive shapes the fused
    # size-1/2/4 programs serve).  Env: GUBER_EXPRESS_MAX_LANES.
    express_max_lanes: int = 4
    # Host-side scalar fast path for singleton checks on CPU backends
    # (ops/scalar.py): skip device dispatch entirely, same ticket-order
    # commit discipline.  Only meaningful with express on; exists as a
    # separate switch so the bypass can be A/B-tested with and without
    # the scalar slot.  Env: GUBER_EXPRESS_SCALAR.
    express_scalar: bool = True

    # -- latency SLO engine (saturation.py) ----------------------------
    # Ingress latency target in ms.  > 0 turns on the SLO burn-rate
    # engine: every V1/GetRateLimits is judged good/bad against the
    # target, multi-window (5m/1h) error-budget burn rates export as
    # gubernator_slo_burn_rate, and a page-level fast burn (>= 14.4x
    # on the 5m window) dumps the flight recorder.  Since the express
    # lane (PR 14) the knob is also BINDING: it caps the effective
    # coalescing window of both ingress batchers at target/2 (half the
    # budget for coalescing, half for dispatch+readback — architecture
    # .md "Express lane"), so occupancy mode yields to latency mode.
    # 0 (default) disables the engine and leaves the window
    # occupancy-sized.  Env: GUBER_LATENCY_TARGET_MS.
    latency_target_ms: float = 0.0
    # SLO objective: the fraction of ingress requests that must answer
    # under the target (the error budget is 1 - objective).
    # Env: GUBER_SLO_OBJECTIVE.
    slo_objective: float = 0.99

    # -- XLA / device telemetry (telemetry.py) -------------------------
    # Compile tracking + recompile-storm detection + per-program launch
    # timings + device memory sampling, exported as gubernator_xla_* /
    # gubernator_device_* and GET /debug/device.  False disables the
    # plane entirely: the launch-site hook degrades to one branch
    # returning a shared no-op (the bench gate pins the overhead ratio
    # >= 0.95 either way).  Env: GUBER_XLA_TELEMETRY.
    xla_telemetry: bool = True
    # Recompile-storm trip: >= xla_storm steady-state compiles within
    # xla_storm_window_s seconds fires the flight-recorder auto-dump.
    # Env: GUBER_XLA_STORM / GUBER_XLA_STORM_WINDOW (window is a Go
    # duration; a bare number means ms).
    xla_storm: int = 3
    xla_storm_window_s: float = 60.0

    # -- cost observatory (profiling.py) -------------------------------
    # Continuous host sampling profiler: a daemon thread folds every
    # thread's stack ~profile_hz times/s into phase-tagged flamegraph
    # windows (GET /debug/pprof).  False compiles the plane out: the
    # sampler tick is one branch, every scope hook one comparison
    # returning a shared no-op (the bench gate pins the overhead ratio
    # >= 0.95 — profiling_overhead_ratio).  Env: GUBER_PROFILE.
    profile: bool = True
    # Sampling rate in Hz (out-of-range [1, 1000] values are rejected
    # loudly at boot, never clamped; the default 67 is deliberately not
    # a divisor of common periodic work, and each tick adds seeded
    # jitter so the sampler cannot phase-lock with a workload).  Env:
    # GUBER_PROFILE_HZ.
    profile_hz: float = 67.0
    # Tenant cost ledger cardinality bound: the top-K rate-limit NAMES
    # keep exact per-tenant accumulators (hits, over-limit, shed,
    # ingress bytes, lane-time/queue shares); everyone else rolls into
    # one `other` bucket, so metric cardinality is K+1 no matter how
    # many distinct names exist.  Env: GUBER_TENANT_TOPK.
    tenant_topk: int = 16

    # -- conservation audit (audit.py) ---------------------------------
    # Always-on windowed reconciliation of the exactly-once ledgers
    # (hits admitted vs dispatched vs applied vs forwarded, GLOBAL
    # carry slack, reshard lane conservation), publishing
    # gubernator_audit_violations_total{invariant} and auto-dumping the
    # flight recorder on any violation.  False stops the checker
    # thread; the ledger counters themselves are always recorded (one
    # int add per batch).  Env: GUBER_AUDIT.
    audit: bool = True
    # Reconciliation cadence in seconds.  Env: GUBER_AUDIT_INTERVAL
    # (a Go duration string; a bare number means ms).
    audit_interval_s: float = 5.0

    # -- elastic membership / live resharding (reshard.py) -------------
    # On a ring delta, drain moved device-resident counters off the old
    # owner and ship them to the new owner as a columnar transfer
    # (GUBC frame kind 4 / PeersV1.TransferOwnership), instead of
    # silently orphaning them — a scale-out event stops being a
    # cluster-wide rate-limit reset.  False = the pre-reshard interop
    # mode: no transfer surface is served (senders negotiate down,
    # exactly like talking to an old build), no handoff is initiated,
    # and a ring change resets moved buckets (legacy semantics).
    # Env: GUBER_RESHARD.
    reshard: bool = True
    # Double-dispatch read window after a membership change: for this
    # long, reads of keys whose owner moved are also peeked (hits=0) at
    # the OLD owner and merged monotonically, so no request observes a
    # reset bucket while the state transfer is in flight.  0 disables
    # the window (transfers still run).  Env: GUBER_RESHARD_HANDOFF.
    reshard_handoff_s: float = 2.0

    # -- durability plane (snapshot.py) --------------------------------
    # Background snapshot cadence in seconds (only active when a
    # snapshot path is configured via GUBER_SNAPSHOT / DaemonConfig
    # .snapshot_path).  0 = shutdown-only snapshots: the file is still
    # written on close()/SIGTERM, just never on a timer.  Env:
    # GUBER_SNAPSHOT_INTERVAL (a Go duration string; bare number = ms).
    snapshot_interval_s: float = 60.0

    # -- incident black box (blackbox.py) ------------------------------
    # Always-on bounded traffic tap at every GUBC wire choke point:
    # per-wire byte-budgeted rings of raw frames, frozen into a
    # crash-safe on-disk bundle whenever a flight-recorder auto-dump
    # trigger fires (breaker-open, audit-violation, slo-fast-burn, ...)
    # or an operator POSTs /debug/incident — replayable with
    # scripts/replay.py.  False = one branch per frame (the tap and
    # trigger hooks go dark; bench-gated blackbox_overhead_ratio).
    # Env: GUBER_BLACKBOX.
    blackbox: bool = True
    # Total in-memory capture budget in MiB, split across the five wire
    # rings (public/peer/global/transfer/region).  Env:
    # GUBER_BLACKBOX_MB (loud reject outside [1, 4096]).
    blackbox_mb: int = 64
    # Bundle retention: oldest incident-* dirs beyond this count are
    # pruned after each write.  Env: GUBER_BLACKBOX_RETAIN (loud reject
    # outside [1, 1024]).
    blackbox_retain: int = 8


@dataclass
class DaemonConfig:
    """config.go:155-202 equivalent.

    `grpc_listen_address` is the gRPC data plane (client V1 + peer
    PeersV1, the reference's GUBER_GRPC_ADDRESS); `listen_address` is
    the HTTP/JSON gateway + /metrics (GUBER_HTTP_ADDRESS).  An empty
    grpc_listen_address binds an ephemeral port on the gateway host.
    """

    listen_address: str = "127.0.0.1:1050"
    grpc_listen_address: str = ""
    # Rotate long-lived gRPC client connections (daemon.go:91-96,
    # GUBER_GRPC_MAX_CONN_AGE_SEC); 0 disables.
    grpc_max_conn_age_s: int = 0
    advertise_address: str = ""
    cache_size: int = 50_000
    back_cache_size: int = 0  # two-tier back tier (0 = single-tier)
    # None = auto-size to cache_size, clamped [4096, 65536] (the
    # reference caps GLOBAL keys only by its shared cache,
    # global.go:83-91).  See ServiceConfig.global_cache_size.
    global_cache_size: "int | None" = None
    # HTTP edge: True serves the gateway from the C++ epoll edge
    # (NativeGatewayServer — better tail latency and per-request
    # overhead; startup error if the native runtime is missing or TLS
    # is on).  Default/False: the stdlib gateway (wins bulk-batch
    # throughput on few-core hosts — measured A/B in RESULTS.md).
    # Env: GUBER_NATIVE_HTTP=1/0.
    native_http: "bool | None" = None
    # Native-edge Python worker count (parse + submit only — the async
    # completion path means workers never block on device rounds, so a
    # handful saturates the submit path; raise on many-core hosts if
    # /metrics shows ingress-queue 503s).  None = NativeGatewayServer
    # default (4).  Env: GUBER_NATIVE_WORKERS.
    native_workers: "int | None" = None
    # Native-edge acceptor sharding: N SO_REUSEPORT listen sockets on
    # the HTTP port, each with its own epoll loop thread, all feeding
    # the one shared device pipeline — the kernel spreads accepted
    # connections across the group, so a single serializing accept/
    # read loop stops being the ingress ceiling.  1 (default) is the
    # classic single loop, behavior-identical to the pre-sharding
    # edge.  Only meaningful with GUBER_NATIVE_HTTP=1.
    # Env: GUBER_ACCEPTORS.
    acceptors: int = 1
    # Same-host UDS lane: when set, the native edge ALSO listens on
    # this AF_UNIX socket path, speaking the identical HTTP/1.1 +
    # GUBC kind-5/6 protocol (the sidecar deployment shape — a
    # same-pod client skips the TCP stack entirely).  Clients target
    # it as `unix:///path` (ColumnsV1Client / V1Client).  A stale
    # socket file at the path is unlinked at startup; "" disables.
    # Only meaningful with GUBER_NATIVE_HTTP=1.  Env: GUBER_UDS_PATH.
    uds_path: str = ""
    # Durability plane (snapshot.py): path of the crash-safe columnar
    # device-state snapshot file.  "" (and the explicit opt-outs "0"/
    # "false"/"off" in the env var) = disabled — every restart is a
    # full reset, exactly the pre-durability daemon.  Written with
    # temp+fsync+rename on close()/SIGTERM and every
    # behaviors.snapshot_interval_s; restored at boot with ONE monotone
    # merge-commit.  Env: GUBER_SNAPSHOT.
    snapshot_path: str = ""
    # Incident black box (blackbox.py): directory incident bundles are
    # written into.  "" (and the boolean-flavored opt-outs in the env
    # var) = no bundles — the in-memory rings still run (and feed
    # /debug/status), there's just nowhere to freeze them to.
    # Env: GUBER_BLACKBOX_DIR.
    blackbox_dir: str = ""
    data_center: str = ""
    behaviors: BehaviorConfig = field(default_factory=BehaviorConfig)
    # Static peer list (the zero-dependency discovery mode; etcd/
    # memberlist/k8s plug in via gubernator_tpu.peers).
    peers: List[PeerInfo] = field(default_factory=list)
    peer_discovery_type: str = "static"  # static | file | etcd | member-list | k8s
    peers_file: str = ""
    # member-list gossip knobs (reference MemberListPoolConfig,
    # memberlist.go:44-66 / config.go:314-317).
    member_list_address: str = ""  # bind host:port, default advertise_host:7946
    member_list_known_nodes: List[str] = field(default_factory=list)
    member_list_node_name: str = ""
    # etcd discovery knobs (reference EtcdPoolConfig, etcd.go:54-72 /
    # config.go:304-312).
    etcd_endpoints: List[str] = field(default_factory=lambda: ["localhost:2379"])
    etcd_key_prefix: str = "/gubernator/peers/"
    etcd_advertise_address: str = ""  # defaults to the daemon advertise address
    # etcd auth + TLS (config.go:309-310, setupEtcdTLS config.go:390-433)
    etcd_user: str = ""
    etcd_password: str = ""
    etcd_tls_enable: bool = False
    etcd_tls_cert: str = ""
    etcd_tls_key: str = ""
    etcd_tls_ca: str = ""
    etcd_tls_skip_verify: bool = False
    # k8s discovery knobs (reference K8sPoolConfig, kubernetes.go:63-72 /
    # config.go:320-328).
    k8s_namespace: str = "default"
    k8s_pod_ip: str = ""
    k8s_pod_port: str = "81"  # reference default (kubernetes.go peer port)
    k8s_selector: str = ""
    k8s_mechanism: str = "endpoints"  # endpoints | pods
    store: object = None
    loader: object = None
    # Deterministic chaos harness: a faults.FaultPlan consulted by every
    # PeerClient this daemon creates and by the gossip prober (None =
    # honor the process-wide faults.install() plan instead).
    fault_plan: object = None  # Optional[faults.FaultPlan]
    # Seed for the SWIM probe-order RNG (gossip.py) so suspect/confirm
    # transitions replay deterministically in chaos tests.  None = a
    # fresh unseeded RNG per node.  Env: GUBER_GOSSIP_SEED.
    gossip_seed: "int | None" = None
    debug: bool = False
    # TLS (reference tls.go); wraps the gateway listener and the peer
    # transport when set.  See gubernator_tpu.tls.TLSConfig.
    tls: object = None  # Optional[tls.TLSConfig]
    devices: Optional[list] = None  # jax devices for the mesh (None = all)
    # Columnar-kernel pad buckets (lane counts) to compile during
    # startup warmup: each pad_size bucket is a distinct XLA program,
    # and on a remote device its first dispatch pays a multi-second
    # executable load — better inside startup than a client deadline.
    # The default covers every bucket up to the 1000-item request cap
    # (pads 64/256/1024), so client and peer RPCs never dispatch cold.
    warmup_shapes: List[int] = field(default_factory=lambda: [1, 250, 1000])

    def resolved_advertise(self) -> str:
        return self.advertise_address or self.listen_address


def _env_bool(merged: "Dict[str, str]", key: str, default: bool) -> bool:
    """Reference getEnvBool semantics: any truthy string enables
    (config.go:444-489); absent keeps the default."""
    v = merged.get(key, "")
    if v == "":
        return default
    return v.lower() in ("true", "1", "yes")


def _env_int(env: Dict[str, str], name: str, default: int) -> int:
    v = env.get(name, "")
    return int(v) if v else default


_DURATION_UNITS_S = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "μs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|μs|ms|s|m|h)")


def parse_duration(v: str) -> float:
    """Go duration string -> seconds: '300ms', '1m30s', '1.5h', with the
    same unit set as time.ParseDuration. A bare number is milliseconds."""
    v = v.strip()
    if not v:
        raise ValueError("empty duration")
    if re.fullmatch(r"\d+(?:\.\d+)?", v):
        return float(v) / 1000.0
    pos, total = 0, 0.0
    for m in _DURATION_RE.finditer(v):
        if m.start() != pos:
            break
        total += float(m.group(1)) * _DURATION_UNITS_S[m.group(2)]
        pos = m.end()
    if pos != len(v):
        raise ValueError(f"invalid duration '{v}'")
    return total


def _env_float_ms(env: Dict[str, str], name: str, default_s: float) -> float:
    """GUBER durations are Go duration strings in the reference
    (config.go uses time.ParseDuration); a bare number means ms."""
    v = env.get(name, "")
    if not v:
        return default_s
    try:
        return parse_duration(v)
    except ValueError as e:
        raise ValueError(f"{name}: {e}") from None


def from_env_file(path: str) -> Dict[str, str]:
    """KEY=VALUE lines -> dict (config.go:493-521); '#' comments skipped."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise ValueError(f"malformed line in env file: '{line}'")
            k, _, v = line.partition("=")
            out[k.strip()] = v.strip()
    return out


def setup_daemon_config(
    config_file: str = "", env: Optional[Dict[str, str]] = None
) -> DaemonConfig:
    """Env-file -> GUBER_* env vars -> defaults (config.go:220-388)."""
    merged: Dict[str, str] = {}
    if config_file:
        merged.update(from_env_file(config_file))
    merged.update({k: v for k, v in (env or os.environ).items() if k.startswith("GUBER_")})

    conf = DaemonConfig()
    conf.listen_address = merged.get("GUBER_HTTP_ADDRESS") or conf.listen_address
    conf.grpc_listen_address = merged.get("GUBER_GRPC_ADDRESS", "")
    conf.grpc_max_conn_age_s = _env_int(merged, "GUBER_GRPC_MAX_CONN_AGE_SEC", 0)
    conf.advertise_address = merged.get(
        "GUBER_ADVERTISE_ADDRESS", merged.get("GUBER_GRPC_ADVERTISE_ADDRESS", "")
    )
    conf.cache_size = _env_int(merged, "GUBER_CACHE_SIZE", conf.cache_size)
    conf.back_cache_size = _env_int(
        merged, "GUBER_BACK_CACHE_SIZE", conf.back_cache_size
    )
    conf.global_cache_size = _env_int(
        merged, "GUBER_GLOBAL_CACHE_SIZE", conf.global_cache_size
    )
    v = merged.get("GUBER_NATIVE_HTTP", "")
    if v:
        conf.native_http = v.strip().lower() in ("1", "true", "yes", "on")
    conf.native_workers = _env_int(
        merged, "GUBER_NATIVE_WORKERS", conf.native_workers
    )
    conf.acceptors = _env_int(merged, "GUBER_ACCEPTORS", conf.acceptors)
    # Loud, not clamped: GUBER_ACCEPTORS=0 would accept-but-never-
    # serve and >64 is a misconfiguration, not a scaling plan (each
    # acceptor is a native thread).
    if not 1 <= conf.acceptors <= 64:
        raise ValueError(
            f"GUBER_ACCEPTORS must be in [1, 64], got '{conf.acceptors}'"
        )
    conf.uds_path = merged.get("GUBER_UDS_PATH", conf.uds_path)
    conf.data_center = merged.get("GUBER_DATA_CENTER", "")
    if merged.get("GUBER_WARMUP_SHAPES"):
        conf.warmup_shapes = [
            int(s) for s in merged["GUBER_WARMUP_SHAPES"].split(",") if s.strip()
        ]
    conf.debug = merged.get("GUBER_DEBUG", "").lower() in ("true", "1", "yes")
    conf.peer_discovery_type = merged.get("GUBER_PEER_DISCOVERY_TYPE", "static")
    if conf.peer_discovery_type not in ("static", "file", "etcd", "member-list", "k8s"):
        raise ValueError(
            f"GUBER_PEER_DISCOVERY_TYPE is invalid; expected 'static', 'file', "
            f"'etcd', 'member-list' or 'k8s' got '{conf.peer_discovery_type}'"
        )
    conf.peers_file = merged.get("GUBER_PEERS_FILE", "")
    conf.member_list_address = merged.get("GUBER_MEMBERLIST_ADDRESS", "")
    conf.member_list_known_nodes = [
        n.strip()
        for n in merged.get("GUBER_MEMBERLIST_KNOWN_NODES", "").split(",")
        if n.strip()
    ]
    conf.member_list_node_name = merged.get("GUBER_MEMBERLIST_NODE_NAME", "")
    etcd_endpoints = merged.get("GUBER_ETCD_ENDPOINTS", "")
    if etcd_endpoints:
        conf.etcd_endpoints = [e.strip() for e in etcd_endpoints.split(",") if e.strip()]
    conf.etcd_key_prefix = merged.get("GUBER_ETCD_KEY_PREFIX", conf.etcd_key_prefix)
    conf.etcd_advertise_address = merged.get("GUBER_ETCD_ADVERTISE_ADDRESS", "")
    conf.etcd_user = merged.get("GUBER_ETCD_USER", conf.etcd_user)
    conf.etcd_password = merged.get("GUBER_ETCD_PASSWORD", conf.etcd_password)
    conf.etcd_tls_enable = _env_bool(merged, "GUBER_ETCD_TLS_ENABLE", conf.etcd_tls_enable)
    conf.etcd_tls_cert = merged.get("GUBER_ETCD_TLS_CERT", conf.etcd_tls_cert)
    conf.etcd_tls_key = merged.get("GUBER_ETCD_TLS_KEY", conf.etcd_tls_key)
    conf.etcd_tls_ca = merged.get("GUBER_ETCD_TLS_CA", conf.etcd_tls_ca)
    conf.etcd_tls_skip_verify = _env_bool(
        merged, "GUBER_ETCD_TLS_SKIP_VERIFY", conf.etcd_tls_skip_verify
    )
    conf.k8s_namespace = merged.get("GUBER_K8S_NAMESPACE", conf.k8s_namespace)
    conf.k8s_pod_ip = merged.get("GUBER_K8S_POD_IP", "")
    conf.k8s_pod_port = merged.get("GUBER_K8S_POD_PORT", "") or conf.k8s_pod_port
    conf.k8s_selector = merged.get("GUBER_K8S_ENDPOINTS_SELECTOR", "")
    from .k8s_pool import watch_mechanism_from_string

    try:
        conf.k8s_mechanism = watch_mechanism_from_string(
            merged.get("GUBER_K8S_WATCH_MECHANISM", "")
        )
    except ValueError:
        raise ValueError(
            "`GUBER_K8S_WATCH_MECHANISM` needs to be either 'endpoints' or "
            "'pods' (defaults to 'endpoints')"
        ) from None
    if conf.peer_discovery_type == "k8s" and not conf.k8s_selector:
        raise ValueError(
            "when using k8s for peer discovery, you MUST provide a "
            "`GUBER_K8S_ENDPOINTS_SELECTOR` to select the gubernator peers "
            "from the endpoints listing"
        )  # config.go:356-360
    if conf.peer_discovery_type == "member-list" and not conf.member_list_known_nodes:
        raise ValueError(
            "when member-list is used for peer discovery, you MUST provide a "
            "list of known nodes via GUBER_MEMBERLIST_KNOWN_NODES"
        )  # config.go:366-370

    b = conf.behaviors
    b.batch_timeout_s = _env_float_ms(merged, "GUBER_BATCH_TIMEOUT", b.batch_timeout_s)
    b.batch_wait_s = _env_float_ms(merged, "GUBER_BATCH_WAIT", b.batch_wait_s)
    b.batch_limit = _env_int(merged, "GUBER_BATCH_LIMIT", b.batch_limit)
    if b.batch_limit > MAX_BATCH_SIZE:
        raise ValueError(f"GUBER_BATCH_LIMIT cannot exceed '{MAX_BATCH_SIZE}'")
    b.ingress_queue_lanes = _env_int(
        merged, "GUBER_INGRESS_QUEUE_LANES", b.ingress_queue_lanes
    )
    b.peer_columns = _env_bool(merged, "GUBER_PEER_COLUMNS", b.peer_columns)
    b.ingress_columns = _env_bool(
        merged, "GUBER_INGRESS_COLUMNS", b.ingress_columns
    )
    b.native_ingress = _env_bool(
        merged, "GUBER_NATIVE_INGRESS", b.native_ingress
    )
    b.global_timeout_s = _env_float_ms(merged, "GUBER_GLOBAL_TIMEOUT", b.global_timeout_s)
    b.global_sync_wait_s = _env_float_ms(
        merged, "GUBER_GLOBAL_SYNC_WAIT", b.global_sync_wait_s
    )
    b.global_batch_limit = _env_int(
        merged, "GUBER_GLOBAL_BATCH_LIMIT", b.global_batch_limit
    )
    if b.global_batch_limit > MAX_BATCH_SIZE:
        raise ValueError(f"GUBER_GLOBAL_BATCH_LIMIT cannot exceed '{MAX_BATCH_SIZE}'")
    b.global_columns = _env_bool(merged, "GUBER_GLOBAL_COLUMNS", b.global_columns)
    b.global_fanout = _env_int(merged, "GUBER_GLOBAL_FANOUT", b.global_fanout)
    if b.global_fanout < 1:
        raise ValueError("GUBER_GLOBAL_FANOUT must be >= 1")
    b.multi_region_timeout_s = _env_float_ms(
        merged, "GUBER_MULTI_REGION_TIMEOUT", b.multi_region_timeout_s
    )
    if b.multi_region_timeout_s <= 0:
        raise ValueError("GUBER_MULTI_REGION_TIMEOUT must be > 0")
    b.multi_region_sync_wait_s = _env_float_ms(
        merged, "GUBER_MULTI_REGION_SYNC_WAIT", b.multi_region_sync_wait_s
    )
    if b.multi_region_sync_wait_s <= 0:
        raise ValueError("GUBER_MULTI_REGION_SYNC_WAIT must be > 0")
    b.multi_region_batch_limit = _env_int(
        merged, "GUBER_MULTI_REGION_BATCH_LIMIT", b.multi_region_batch_limit
    )
    # The federation accumulator HONORS the limit as its queue-full
    # early flush (0 = window-only); a negative value is a config bug,
    # not a mode (and >MAX_BATCH_SIZE would make the CLASSIC fallback
    # chunks unsendable to a pre-federation peer).
    if b.multi_region_batch_limit < 0:
        raise ValueError("GUBER_MULTI_REGION_BATCH_LIMIT must be >= 0")
    if b.multi_region_batch_limit > MAX_BATCH_SIZE:
        raise ValueError(
            f"GUBER_MULTI_REGION_BATCH_LIMIT cannot exceed '{MAX_BATCH_SIZE}'"
        )
    b.region_columns = _env_bool(
        merged, "GUBER_REGION_COLUMNS", b.region_columns
    )
    b.circuit_threshold = _env_int(
        merged, "GUBER_CIRCUIT_THRESHOLD", b.circuit_threshold
    )
    if b.circuit_threshold < 1:
        raise ValueError("GUBER_CIRCUIT_THRESHOLD must be >= 1")
    b.circuit_open_interval_s = _env_float_ms(
        merged, "GUBER_CIRCUIT_OPEN_INTERVAL", b.circuit_open_interval_s
    )
    b.forward_retry_limit = _env_int(
        merged, "GUBER_FORWARD_RETRY_LIMIT", b.forward_retry_limit
    )
    b.retry_backoff_base_s = _env_float_ms(
        merged, "GUBER_RETRY_BACKOFF_BASE", b.retry_backoff_base_s
    )
    b.retry_backoff_max_s = _env_float_ms(
        merged, "GUBER_RETRY_BACKOFF_MAX", b.retry_backoff_max_s
    )
    b.global_send_retries = _env_int(
        merged, "GUBER_GLOBAL_SEND_RETRIES", b.global_send_retries
    )
    b.xla_telemetry = _env_bool(merged, "GUBER_XLA_TELEMETRY", b.xla_telemetry)
    b.xla_storm = _env_int(merged, "GUBER_XLA_STORM", b.xla_storm)
    if b.xla_storm < 1:
        raise ValueError("GUBER_XLA_STORM must be >= 1")
    b.xla_storm_window_s = _env_float_ms(
        merged, "GUBER_XLA_STORM_WINDOW", b.xla_storm_window_s
    )
    if b.xla_storm_window_s <= 0:
        raise ValueError("GUBER_XLA_STORM_WINDOW must be > 0")
    b.profile = _env_bool(merged, "GUBER_PROFILE", b.profile)
    v = merged.get("GUBER_PROFILE_HZ", "")
    if v:
        try:
            hz = float(v)
        except ValueError:
            raise ValueError(
                f"GUBER_PROFILE_HZ must be a number (Hz), got '{v}'"
            ) from None
        # Loud, not clamped: GUBER_PROFILE_HZ=5000 silently sampling at
        # the 1000 cap would hide a 5x misconfiguration; 0 meaning
        # "off" is GUBER_PROFILE=0's job, not a magic rate.
        if not 1.0 <= hz <= 1000.0:
            raise ValueError(
                f"GUBER_PROFILE_HZ must be in [1, 1000], got '{v}'"
            )
        b.profile_hz = hz
    b.tenant_topk = _env_int(merged, "GUBER_TENANT_TOPK", b.tenant_topk)
    if not 1 <= b.tenant_topk <= 1024:
        # The bound IS the point of the knob: 0 tenants tracks nothing
        # and >1024 is an unbounded-cardinality config bug.
        raise ValueError(
            f"GUBER_TENANT_TOPK must be in [1, 1024], got '{b.tenant_topk}'"
        )
    b.audit = _env_bool(merged, "GUBER_AUDIT", b.audit)
    b.audit_interval_s = _env_float_ms(
        merged, "GUBER_AUDIT_INTERVAL", b.audit_interval_s
    )
    if b.audit_interval_s <= 0:
        raise ValueError("GUBER_AUDIT_INTERVAL must be > 0")
    b.reshard = _env_bool(merged, "GUBER_RESHARD", b.reshard)
    b.reshard_handoff_s = _env_float_ms(
        merged, "GUBER_RESHARD_HANDOFF", b.reshard_handoff_s
    )
    if b.reshard_handoff_s < 0:
        raise ValueError("GUBER_RESHARD_HANDOFF must be >= 0")
    v = merged.get("GUBER_SNAPSHOT", "").strip()
    # GUBER_SNAPSHOT=0 (the chaos suite's pre-durability mode) and its
    # boolean-flavored siblings read as "disabled", not as a filename.
    conf.snapshot_path = (
        "" if v.lower() in ("", "0", "false", "off", "no") else v
    )
    b.snapshot_interval_s = _env_float_ms(
        merged, "GUBER_SNAPSHOT_INTERVAL", b.snapshot_interval_s
    )
    if b.snapshot_interval_s < 0:
        raise ValueError("GUBER_SNAPSHOT_INTERVAL must be >= 0")
    b.blackbox = _env_bool(merged, "GUBER_BLACKBOX", b.blackbox)
    b.blackbox_mb = _env_int(merged, "GUBER_BLACKBOX_MB", b.blackbox_mb)
    if not 1 <= b.blackbox_mb <= 4096:
        # Loud, not clamped: a 0 budget silently capturing nothing
        # while the tap reads enabled would surface as an empty bundle
        # at the worst possible moment (mid-incident).
        raise ValueError(
            f"GUBER_BLACKBOX_MB must be in [1, 4096], got '{b.blackbox_mb}'"
        )
    b.blackbox_retain = _env_int(
        merged, "GUBER_BLACKBOX_RETAIN", b.blackbox_retain
    )
    if not 1 <= b.blackbox_retain <= 1024:
        raise ValueError(
            f"GUBER_BLACKBOX_RETAIN must be in [1, 1024], "
            f"got '{b.blackbox_retain}'"
        )
    v = merged.get("GUBER_BLACKBOX_DIR", "").strip()
    # Same boolean-flavored opt-outs as GUBER_SNAPSHOT: "0" reads as
    # "no bundle dir", not as a directory named 0.
    conf.blackbox_dir = (
        "" if v.lower() in ("", "0", "false", "off", "no") else v
    )
    v = merged.get("GUBER_TRACE_SAMPLE", "")
    if v:
        try:
            rate = float(v)
        except ValueError:
            rate = -1.0
        if not 0.0 <= rate <= 1.0:
            # Loud, not clamped: GUBER_TRACE_SAMPLE=5 meaning "5%"
            # silently tracing EVERY request is a 20x surprise.
            raise ValueError(
                f"GUBER_TRACE_SAMPLE must be a float in [0, 1], got '{v}'"
            )
        b.trace_sample = rate
    b.express = _env_bool(merged, "GUBER_EXPRESS", b.express)
    b.express_queue_depth = _env_int(
        merged, "GUBER_EXPRESS_QUEUE_DEPTH", b.express_queue_depth
    )
    # Loud, not clamped: 0 would make the bypass unreachable while the
    # knob reads enabled (GUBER_EXPRESS=0 is the off switch), and a
    # threshold past the ingress-queue cap is a misconfiguration, not
    # a latency plan.
    if not 1 <= b.express_queue_depth <= 1_000_000:
        raise ValueError(
            f"GUBER_EXPRESS_QUEUE_DEPTH must be in [1, 1000000], "
            f"got '{b.express_queue_depth}'"
        )
    b.express_max_lanes = _env_int(
        merged, "GUBER_EXPRESS_MAX_LANES", b.express_max_lanes
    )
    if not 1 <= b.express_max_lanes <= 64:
        # The bypass exists for the small interactive shapes the warm
        # fused size-1/2/4 programs serve; >64 lanes would bypass into
        # a fresh pad bucket and compile mid-traffic.
        raise ValueError(
            f"GUBER_EXPRESS_MAX_LANES must be in [1, 64], "
            f"got '{b.express_max_lanes}'"
        )
    b.express_scalar = _env_bool(
        merged, "GUBER_EXPRESS_SCALAR", b.express_scalar
    )
    v = merged.get("GUBER_LATENCY_TARGET_MS", "")
    if v:
        try:
            target = float(v)
        except ValueError:
            raise ValueError(
                f"GUBER_LATENCY_TARGET_MS must be a number (ms), got '{v}'"
            ) from None
        if target < 0:
            raise ValueError("GUBER_LATENCY_TARGET_MS must be >= 0")
        b.latency_target_ms = target
    v = merged.get("GUBER_SLO_OBJECTIVE", "")
    if v:
        try:
            obj = float(v)
        except ValueError:
            obj = -1.0
        if not 0.0 < obj < 1.0:
            # Loud, not clamped: GUBER_SLO_OBJECTIVE=99 meaning "99%"
            # would silently demand a zero error budget.
            raise ValueError(
                f"GUBER_SLO_OBJECTIVE must be a fraction in (0, 1), got '{v}'"
            )
        b.slo_objective = obj
    conf.gossip_seed = _env_int(merged, "GUBER_GOSSIP_SEED", conf.gossip_seed)

    # Static peers: GUBER_STATIC_PEERS=grpcAddr[|httpAddr],... (our
    # addition for the zero-dependency mode; the reference's equivalent
    # is the member-list seed GUBER_MEMBERLIST_KNOWN_NODES).  Entries
    # are gRPC data-plane addresses, like the reference's peer lists;
    # the optional |httpAddr names the peer's gateway for the HTTP
    # fallback transport (required by insecure_skip_verify TLS).
    static = merged.get("GUBER_STATIC_PEERS", "")
    if static:
        conf.peers = []
        for entry in static.split(","):
            entry = entry.strip()
            if not entry:
                continue
            grpc_addr, _, http_addr = entry.partition("|")
            conf.peers.append(
                PeerInfo(
                    grpc_address=grpc_addr.strip(),
                    http_address=http_addr.strip() or grpc_addr.strip(),
                )
            )

    tls_keys = (
        "GUBER_TLS_CA", "GUBER_TLS_CA_KEY", "GUBER_TLS_CERT", "GUBER_TLS_KEY",
        "GUBER_TLS_AUTO", "GUBER_TLS_CLIENT_AUTH", "GUBER_TLS_CLIENT_AUTH_CA_CERT",
        "GUBER_TLS_CLIENT_AUTH_CERT", "GUBER_TLS_CLIENT_AUTH_KEY",
        "GUBER_TLS_INSECURE_SKIP_VERIFY",
    )
    if any(merged.get(k) for k in tls_keys):
        from .tls import TLSConfig

        conf.tls = TLSConfig(
            ca_file=merged.get("GUBER_TLS_CA", ""),
            ca_key_file=merged.get("GUBER_TLS_CA_KEY", ""),
            cert_file=merged.get("GUBER_TLS_CERT", ""),
            key_file=merged.get("GUBER_TLS_KEY", ""),
            auto_tls=merged.get("GUBER_TLS_AUTO", "").lower() in ("true", "1", "yes"),
            client_auth=merged.get("GUBER_TLS_CLIENT_AUTH", ""),
            client_auth_ca_file=merged.get("GUBER_TLS_CLIENT_AUTH_CA_CERT", ""),
            client_auth_cert_file=merged.get("GUBER_TLS_CLIENT_AUTH_CERT", ""),
            client_auth_key_file=merged.get("GUBER_TLS_CLIENT_AUTH_KEY", ""),
            insecure_skip_verify=merged.get(
                "GUBER_TLS_INSECURE_SKIP_VERIFY", ""
            ).lower() in ("true", "1", "yes"),
        )
    return conf
