# Build the gubernator-tpu daemon image (reference Dockerfile:1-32 uses
# a Go builder + scratch image; a Python/JAX runtime needs a slim python
# base instead).  The TPU runtime libraries come from the host/node
# (e.g. the libtpu container toolkit on GKE TPU node pools); on CPU-only
# nodes the same image serves with XLA's host platform.
FROM python:3.12-slim AS builder

WORKDIR /src
COPY gubernator_tpu/ gubernator_tpu/
COPY setup.py README.md ./
RUN pip install --no-cache-dir build && python -m build --wheel

FROM python:3.12-slim

# jax/numpy are the only hard runtime deps; grpcio serves the data
# plane.  Pin jax to the version the image is validated against.
RUN pip install --no-cache-dir "jax>=0.4.30" "numpy>=1.26" "grpcio>=1.60"
COPY --from=builder /src/dist/*.whl /tmp/
RUN pip install --no-cache-dir /tmp/*.whl && rm /tmp/*.whl

# HTTP/JSON gateway + /metrics
EXPOSE 1050
# gRPC data plane (V1 + PeersV1)
EXPOSE 1051
# member-list gossip plane
EXPOSE 7946

ENV GUBER_HTTP_ADDRESS=0.0.0.0:1050 \
    GUBER_GRPC_ADDRESS=0.0.0.0:1051

ENTRYPOINT ["python", "-m", "gubernator_tpu.cmd.server"]
