"""Benchmark suite: the five BASELINE.json configs.

Each config prints one JSON line (same shape as bench.py).  Run all
with `python bench_full.py`, or one with `--config N`.  On a single
real TPU chip configs 4-5 shrink their cluster/mesh dimensions to what
the host offers; on the virtual CPU mesh (JAX_PLATFORMS=cpu +
--xla_force_host_platform_device_count=8) config 4 exercises the full
8-shard collective path.

Reference harness equivalents: benchmark_test.go:28-138 (configs 1),
its in-process cluster (config 5), and the Zipf/Gregorian/GLOBAL
configs enumerated in BASELINE.json.
"""

import argparse
import json
import time

import numpy as np

BASELINE_RPS = 2000.0  # reference single-node req/s (README.md:96-100)
NOW = 1_700_000_000_000


def _emit(name, checks, seconds, **extra):
    cps = checks / seconds
    print(
        json.dumps(
            {
                "metric": f"cfg{name}_checks_per_sec",
                "value": round(cps, 1),
                "unit": "checks/s",
                "vs_baseline": round(cps / BASELINE_RPS, 2),
                **extra,
            }
        ),
        flush=True,
    )


SCALE = 1.0  # --smoke shrinks every config for CI-speed correctness runs


def _sz(n, lo=64):
    return max(int(n * SCALE), lo)


def _zipf_ids(rng, n_keys, batch, hot_frac=0.1, hot_traffic=0.8):
    hot = rng.randint(0, max(int(n_keys * hot_frac), 1), size=batch)
    cold = rng.randint(0, n_keys, size=batch)
    return np.where(rng.random(batch) < hot_traffic, hot, cold)


def _pump(store, keys, cols, iters, warm=2):
    """Pipelined steady-state pump over one prepared batch."""
    def dispatch(i):
        return store.apply_columns_async(keys, now_ms=NOW + i, **cols)

    for i in range(warm):
        dispatch(i).result()
    t0 = time.perf_counter()
    pending = None
    for i in range(iters):
        h = dispatch(warm + i)
        if pending is not None:
            pending.result()
        pending = h
    pending.result()
    return time.perf_counter() - t0


def config1():
    """Token bucket, single node, NO_BATCHING, 1k unique keys."""
    from gubernator_tpu.models.shard import ShardStore
    from gubernator_tpu.types import Behavior

    rng = np.random.RandomState(1)
    batch, iters = _sz(65_536), 10
    key_ids = rng.randint(0, 1000, size=batch)
    keys = [f"c1:{k}" for k in key_ids]
    cols = dict(
        algorithm=np.zeros(batch, np.int32),
        behavior=np.full(batch, int(Behavior.NO_BATCHING), np.int32),
        hits=np.ones(batch, np.int64),
        limit=np.full(batch, 100_000, np.int64),
        duration=np.full(batch, 60_000, np.int64),
    )
    store = ShardStore(capacity=4096)
    dt = _pump(store, keys, cols, iters)
    _emit(1, batch * iters, dt, keys_unique=1000)


def config2():
    """Leaky bucket, BATCHING, 1M unique keys, Zipf-distributed."""
    from gubernator_tpu.models.shard import ShardStore

    rng = np.random.RandomState(2)
    batch, iters = _sz(131_072), 8
    n_keys = _sz(1_000_000)
    key_ids = _zipf_ids(rng, n_keys, batch)
    keys = [f"c2:{k}" for k in key_ids]
    cols = dict(
        algorithm=np.ones(batch, np.int32),  # LEAKY
        behavior=np.zeros(batch, np.int32),  # BATCHING is the zero value
        hits=np.ones(batch, np.int64),
        limit=np.full(batch, 1_000_000, np.int64),
        duration=np.full(batch, 3_600_000, np.int64),
    )
    store = ShardStore(capacity=_sz(1_200_000))
    dt = _pump(store, keys, cols, iters)
    _emit(2, batch * iters, dt, keys_unique=n_keys)


def config3():
    """Mixed token+leaky with Gregorian daily/monthly resets, 10M keyspace.

    Gregorian lanes carry precomputed calendar expiries (the host side
    of DURATION_IS_GREGORIAN), which exceed the int32 delta and drive
    the wide kernel path; the table is smaller than the keyspace so LRU
    eviction churn is part of the measurement."""
    from gubernator_tpu.models.shard import GregResolver, ShardStore
    from gubernator_tpu.types import Behavior
    from gubernator_tpu.utils import gregorian

    rng = np.random.RandomState(3)
    batch, iters = _sz(131_072), 6
    n_keys = _sz(10_000_000)
    key_ids = _zipf_ids(rng, n_keys, batch)
    keys = [f"c3:{k}" for k in key_ids]
    greg = GregResolver(NOW)
    ge_d, gd_d = greg.resolve(gregorian.GREGORIAN_DAYS)
    ge_m, gd_m = greg.resolve(gregorian.GREGORIAN_MONTHS)
    monthly = (key_ids % 2).astype(bool)
    cols = dict(
        algorithm=(key_ids % 2).astype(np.int32),
        behavior=np.full(batch, int(Behavior.DURATION_IS_GREGORIAN), np.int32),
        hits=np.ones(batch, np.int64),
        limit=np.full(batch, 1_000_000, np.int64),
        duration=np.where(monthly, gregorian.GREGORIAN_MONTHS, gregorian.GREGORIAN_DAYS).astype(np.int64),
        greg_expire=np.where(monthly, ge_m, ge_d).astype(np.int64),
        greg_duration=np.where(monthly, gd_m, gd_d).astype(np.int64),
    )
    cap = _sz(2_000_000)
    store = ShardStore(capacity=cap)
    dt = _pump(store, keys, cols, iters)
    _emit(3, batch * iters, dt, keyspace=n_keys, table_capacity=cap)

    # 3b: the same churny workload on the TWO-TIER mesh store (small
    # front prices every scatter; the 10M keyspace churns rows through
    # the demote/promote move program into the device-resident back
    # tier).  Front sized to hold a batch's unique keys with headroom.
    import jax

    from gubernator_tpu.parallel.mesh import MeshBucketStore, make_mesh

    front = _sz(262_144)
    back = max(cap - front, 0)
    two = MeshBucketStore(
        capacity_per_shard=front,
        back_capacity_per_shard=back,
        mesh=make_mesh(jax.devices()[:1]),
    )
    # Rotating key windows: unlike _pump's single replayed batch, each
    # dispatch brings a fresh slice of the 10M keyspace, so front
    # evictions demote continuously — the churn path is the point.
    n_windows = 4
    window_batches = []
    for w in range(n_windows):
        ids_w = (key_ids + w * (n_keys // n_windows)) % n_keys
        window_batches.append(([f"c3:{k}" for k in ids_w], cols))

    def dispatch(i):
        ks, c = window_batches[i % n_windows]
        return two.apply_columns_async(ks, now_ms=NOW + i, **c)

    for i in range(n_windows):
        dispatch(i).result()  # compile + first-fill every window
    t0 = time.perf_counter()
    pending = None
    for i in range(iters):
        h = dispatch(i)
        if pending is not None:
            pending.result()
        pending = h
    pending.result()
    dt = time.perf_counter() - t0
    stats = [t.tier_stats for t in two.tables]
    _emit("3b_two_tier", batch * iters, dt, keyspace=n_keys,
          front_capacity=front, back_capacity=back,
          demotions=sum(s[2] for s in stats),
          promotions=sum(s[3] for s in stats),
          back_evictions=sum(s[4] for s in stats))


def config4():
    """GLOBAL behavior on the device mesh: hot-key skew answered from
    replica caches, periodic sync collectives converging the counters
    across shards."""
    import jax

    from gubernator_tpu.parallel.mesh import MeshBucketStore
    from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

    n_dev = len(jax.devices())
    store = MeshBucketStore(capacity_per_shard=8192, g_capacity=512)
    rng = np.random.RandomState(4)
    batch, iters = _sz(2048), 6
    reqs_proto = [
        RateLimitRequest(
            name="c4",
            unique_key=f"hot{k}",
            hits=1,
            limit=10_000_000,
            duration=3_600_000,
            algorithm=Algorithm.TOKEN_BUCKET,
            behavior=Behavior.GLOBAL,
        )
        for k in range(64)  # 64 hot GLOBAL keys
    ]
    ids = rng.randint(0, 64, size=batch)
    batch_reqs = [reqs_proto[i] for i in ids]
    store.apply(batch_reqs, NOW)
    store.sync_globals(NOW)
    # Stress cadence: one sync collective after EVERY batch (two device
    # round trips per batch — the convergence-latency worst case).
    t0 = time.perf_counter()
    syncs = 0
    for i in range(iters):
        store.apply(batch_reqs, NOW + 1 + i, home_shard=i % n_dev)
        res = store.sync_globals(NOW + 1 + i)
        syncs += res.broadcast_count
    dt = time.perf_counter() - t0
    _emit(4, batch * iters, dt, shards=n_dev, broadcasts=syncs, sync_every=1)
    # Deployment cadence: syncs amortize over the GlobalSyncWait window
    # (several batches per sync), the configuration GLOBAL is meant for.
    t0 = time.perf_counter()
    syncs = 0
    for i in range(iters * 4):
        store.apply(batch_reqs, NOW + 100 + i, home_shard=i % n_dev)
        if i % 4 == 3:
            syncs += store.sync_globals(NOW + 100 + i).broadcast_count
    dt = time.perf_counter() - t0
    _emit("4_amortized", batch * iters * 4, dt, shards=n_dev,
          broadcasts=syncs, sync_every=4)
    # Device-only cost of ONE sync collective + the window the
    # GlobalManager auto-tuner would derive from it.  Measured on a
    # FRESH same-shape store: measure_sync_cost_s refuses stores with
    # live GLOBAL traffic (its raw timed syncs would drain their
    # device-side hit accumulations without the host legs), and the
    # collective's cost depends on g_capacity, not on which gslots are
    # active — the program scans all of them every pass.
    from gubernator_tpu.service import GlobalManager

    cal = MeshBucketStore(
        capacity_per_shard=store.capacity_per_shard,
        g_capacity=store.g_capacity,
    )
    cost_s = cal.measure_sync_cost_s(NOW + 10_000)
    g_active = max(len(store.gtable.active_gslots()), 1)
    print(
        json.dumps(
            {
                "metric": "global_sync_device_cost_us",
                "value": round(cost_s * 1e6, 1),
                "unit": "us/sync",
                "vs_baseline": 0,
                "us_per_gslot": round(cost_s * 1e6 / g_active, 2),
                "recommended_sync_wait_ms": round(
                    GlobalManager.window_for_cost(cost_s) * 1e3, 1
                ),
                "shards": n_dev,
            }
        ),
        flush=True,
    )


def config5():
    """Service-tier storm across 2 regions: an in-process cluster of
    real daemons (2 DCs), MULTI_REGION OVER_LIMIT traffic through the
    HTTP edge — the reference's loopback-cluster benchmark topology
    (benchmark_test.go ThunderingHeard + cluster/cluster.go)."""
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.cluster import Cluster, fast_test_behaviors
    from gubernator_tpu.types import (
        Algorithm,
        Behavior,
        GetRateLimitsRequest,
        RateLimitRequest,
    )

    # Deployment-tuned peer deadline: each peer-forward leg waits on a
    # device round that costs 100-400ms through the TPU tunnel (vs
    # single-digit ms locally attached), and a 100-way storm stacks
    # several rounds of queueing on top.  With the default 5s deadline
    # ~half the forwarded lanes die as DEADLINE_EXCEEDED *error
    # responses* — which earlier rounds silently counted as throughput
    # (round-4's 1,217 number).  Errors are now counted separately and
    # excluded from the headline.
    beh = fast_test_behaviors()
    beh.batch_timeout_s = 30.0
    cl = Cluster().start_with(["", "", "dc-east", "dc-east"], behaviors=beh)
    try:
        # Generous timeout: the first batch shape pays its jit compile.
        clients = [V1Client(d.gateway.address, timeout_s=120.0) for d in cl.daemons]
        batches = []
        rng = np.random.RandomState(5)
        for _ in range(8):
            batches.append(
                GetRateLimitsRequest(
                    requests=[
                        RateLimitRequest(
                            name="c5",
                            unique_key=f"storm{rng.randint(16)}",
                            hits=5,
                            limit=10,  # most responses OVER_LIMIT: the storm
                            duration=60_000,
                            algorithm=Algorithm.TOKEN_BUCKET,
                            behavior=Behavior.MULTI_REGION,
                        )
                        for _ in range(_sz(512))
                    ]
                )
            )
        # warm every daemon's path
        for c in clients:
            c.get_rate_limits(batches[0])
        # Concurrent storm clients at the reference's ThunderingHeard
        # fanout — 100 concurrent callers (benchmark_test.go:110-138) —
        # round-robin across daemons.
        import threading as _th

        N_STORM = 100
        totals = [0, 0, 0]  # ok lanes, over_limit, error lanes
        lock = _th.Lock()

        def _storm(i, b):
            resp = clients[i % len(clients)].get_rate_limits(b)
            o = e = 0
            for r in resp.responses:
                if r.error:
                    e += 1
                elif r.status == 1:
                    o += 1
            with lock:
                totals[0] += len(resp.responses) - e
                totals[1] += o
                totals[2] += e

        # Untimed concurrent warm epoch: 100-way coalescing produces
        # pad shapes the serial warm loop never dispatches, and a cold
        # shape's first dispatch pays a multi-second remote executable
        # load that would dominate the timed epoch.
        warm_ts = [
            _th.Thread(target=_storm, args=(i, batches[i % len(batches)]))
            for i in range(N_STORM)
        ]
        for t in warm_ts:
            t.start()
        for t in warm_ts:
            t.join()
        totals[0] = totals[1] = totals[2] = 0
        t0 = time.perf_counter()
        ts = [
            _th.Thread(target=_storm, args=(i, batches[i % len(batches)]))
            for i in range(N_STORM)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        # Headline counts only non-error lanes; error_lanes must be 0
        # for the number to stand (the reference's bench never counts
        # failed requests as served traffic).
        _emit(5, totals[0], dt, regions=2, daemons=len(cl.daemons),
              over_limit=totals[1], error_lanes=totals[2],
              concurrency=len(ts))

        # Plain storm (no MULTI_REGION): max-size batches of locally-mixed
        # keys through ONE daemon's gateway — the columnar ingress path
        # end-to-end (JSON -> columns -> fused kernel -> JSON), directly
        # comparable to the reference's >2,000 req/s single-node number.
        plain_iters = 12
        plain_batches = [
            GetRateLimitsRequest(
                requests=[
                    RateLimitRequest(
                        name="c5p",
                        unique_key=f"plain{rng.randint(4096)}",
                        hits=1,
                        limit=1_000_000,
                        duration=3_600_000,
                        algorithm=Algorithm.TOKEN_BUCKET,
                    )
                    for _ in range(_sz(1000, lo=16))
                ]
            )
            for _ in range(plain_iters)
        ]
        clients[0].get_rate_limits(plain_batches[0])  # warm the batch shape
        # 100 concurrent clients through ONE gateway (ThunderingHeard
        # fanout parity; the coalescing window merges them into shared
        # dispatches); untimed warm epoch first so coalesced pad shapes
        # don't compile inside the timing.
        N_PLAIN = 100

        def _plain(tid, iters, out=None):
            c = 0
            for i in range(iters):
                c += len(clients[0].get_rate_limits(
                    plain_batches[(tid * 5 + i) % plain_iters]).responses)
            if out is not None:
                with lock:
                    out[0] += c

        warm_ts = [_th.Thread(target=_plain, args=(t, 2)) for t in range(N_PLAIN)]
        for t in warm_ts:
            t.start()
        for t in warm_ts:
            t.join()
        totals = [0]
        ts = [
            _th.Thread(target=_plain, args=(t, 3, totals))
            for t in range(N_PLAIN)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        _emit("5_plain", totals[0], dt, daemons=1, clients=N_PLAIN,
              batch=len(plain_batches[0].requests))
    finally:
        cl.stop()


def config6():
    """GLOBAL convergence across 2 real daemons at DEPLOYMENT cadence
    (auto-tuned GlobalSyncWait): sustained GLOBAL throughput through the
    non-owner plus the time for an owner-side OVER_LIMIT to become
    visible in the non-owner's replica cache — the measured twin of the
    reference's TestGlobalRateLimits (functional_test.go:478-546)."""

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon
    from gubernator_tpu.types import (
        Algorithm,
        Behavior,
        GetRateLimitsRequest,
        RateLimitRequest,
    )

    daemons = []
    for _ in range(2):
        daemons.append(
            Daemon(
                DaemonConfig(
                    listen_address="127.0.0.1:0",
                    grpc_listen_address="127.0.0.1:0",
                    cache_size=8192,
                    global_cache_size=512,
                    peer_discovery_type="static",
                )
            ).start()
        )
    try:
        peers = [d.peer_info for d in daemons]
        for d in daemons:
            d.set_peers(peers)
        clients = [V1Client(d.gateway.address, timeout_s=120.0) for d in daemons]

        def owner_of(key):
            for i, d in enumerate(daemons):
                peer = d.service.get_peer(f"g6_{key}")
                if peer.info.is_owner:
                    return i
            return 0

        # a key owned by daemon 0; traffic goes through daemon 1
        key = next(
            f"conv-{k * 7919}" for k in range(256)
            if owner_of(f"conv-{k * 7919}") == 0
        )

        def req(k, hits=1, limit=100_000_000):
            return RateLimitRequest(
                name="g6", unique_key=k, hits=hits, limit=limit,
                duration=3_600_000, algorithm=Algorithm.TOKEN_BUCKET,
                behavior=Behavior.GLOBAL,
            )

        # --- throughput: sustained GLOBAL batches via the NON-owner
        # (answered from the replica cache; hits forward + broadcast on
        # the auto-tuned window) ---
        batch = GetRateLimitsRequest(requests=[req(key) for _ in range(_sz(512))])
        clients[1].get_rate_limits(batch)  # warm
        iters = 20
        t0 = time.perf_counter()
        for _ in range(iters):
            clients[1].get_rate_limits(batch)
        dt = time.perf_counter() - t0
        cps = len(batch.requests) * iters / dt

        # --- convergence lag: drive a key to sticky OVER_LIMIT through
        # the OWNER (drain to 0, then one more hit — the sticky-status
        # path, algorithms.go:112-117), then poll the NON-owner with
        # hits=0 status reads until the owner's broadcast lands in its
        # replica cache.  All mutation goes through the owner so the
        # non-owner's answer-local bucket cannot mask the broadcast ---
        lags = []
        for trial in range(5):
            t = trial
            k = f"{key}-t{t * 104729}"
            while owner_of(k) != 0:
                t += 7
                k = f"{key}-t{t * 104729}"
            drain = GetRateLimitsRequest(requests=[req(k, hits=5, limit=5)])
            clients[0].get_rate_limits(drain)
            over = GetRateLimitsRequest(requests=[req(k, hits=1, limit=5)])
            t0 = time.perf_counter()
            r = clients[0].get_rate_limits(over).responses[0]
            assert r.status == 1, r  # owner is now sticky OVER_LIMIT
            probe = GetRateLimitsRequest(requests=[req(k, hits=0, limit=5)])
            while True:
                r = clients[1].get_rate_limits(probe).responses[0]
                if r.status == 1:
                    lags.append(time.perf_counter() - t0)
                    break
                if time.perf_counter() - t0 > 30:
                    lags.append(None)  # timed out: excluded from stats
                    break
                time.sleep(0.005)
        ok_ms = sorted(x * 1e3 for x in lags if x is not None)
        timeouts = sum(1 for x in lags if x is None)
        print(
            json.dumps(
                {
                    "metric": "cfg6_global_checks_per_sec",
                    "value": round(cps, 1),
                    "unit": "checks/s",
                    "vs_baseline": round(cps / BASELINE_RPS, 2),
                    "daemons": 2,
                    "convergence_ms_p50": round(ok_ms[len(ok_ms) // 2], 1) if ok_ms else -1,
                    "convergence_ms_max": round(ok_ms[-1], 1) if ok_ms else -1,
                    "convergence_timeouts": timeouts,
                    "sync_window": "auto",
                    # Diagnostics: where each daemon's auto window
                    # actually landed (10x the measured sync cost,
                    # clamped [5ms, 1s]).
                    "sync_window_ms": [
                        round(d.service.global_mgr.sync_wait_s * 1e3, 1)
                        for d in daemons
                    ],
                    "sync_cost_ms": [
                        round((d.service.global_mgr.measured_sync_cost_s or 0) * 1e3, 2)
                        for d in daemons
                    ],
                }
            ),
            flush=True,
        )
    finally:
        for c in clients:
            getattr(c, "close", lambda: None)()
        for d in daemons:
            d.close()


def config7():
    """GLOBAL at production working-set scale (round-4 verdict: the 4k
    default gslot table had no evidence past 4,096).  The reference has
    NO separate GLOBAL cap — its GLOBAL keys share the 50k cache
    (global.go:83-91) — so this measures a 50k-key GLOBAL working set:
    ramp, first full sync, steady-state sync with the generation fast
    path (hits-only traffic), and the over-capacity regime where the
    gslot LRU actually evicts."""
    from gubernator_tpu.parallel.mesh import MeshBucketStore
    from gubernator_tpu.service import GlobalManager
    from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

    n_keys = _sz(50_000)
    g_cap = _sz(65_536)
    store = MeshBucketStore(capacity_per_shard=g_cap, g_capacity=g_cap)

    def reqs(lo, hi, hits=1):
        return [
            RateLimitRequest(
                name="c7", unique_key=f"g{k}", hits=hits, limit=1_000_000,
                duration=3_600_000, algorithm=Algorithm.TOKEN_BUCKET,
                behavior=Behavior.GLOBAL,
            )
            for k in range(lo, hi)
        ]

    chunk = 2048
    # Warm the sync program's jit compile outside the timed rows.
    store.apply(reqs(0, 1), NOW)
    store.sync_globals(NOW)

    t0 = time.perf_counter()
    for lo in range(0, n_keys, chunk):
        store.apply(reqs(lo, min(lo + chunk, n_keys)), NOW + lo + 1)
    ramp_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    res = store.sync_globals(NOW + n_keys + 1)
    first_sync_s = time.perf_counter() - t0
    first_broadcasts = res.broadcast_count

    # Steady state: hits only (no mapping churn) — the generation fast
    # path should make the host side O(changed), not O(active).
    steady = []
    for i in range(5):
        store.apply(reqs(0, chunk), NOW + n_keys + 1 + i)
        t0 = time.perf_counter()
        store.sync_globals(NOW + n_keys + 1 + i)
        steady.append(time.perf_counter() - t0)
    steady_ms = sorted(steady)[len(steady) // 2] * 1e3

    cost_s = MeshBucketStore(
        capacity_per_shard=g_cap, g_capacity=g_cap
    ).measure_sync_cost_s(NOW + 10 * n_keys)

    print(
        json.dumps(
            {
                "metric": "cfg7_global_50k_sync_ms",
                "value": round(steady_ms, 2),
                "unit": "ms/steady_sync",
                "vs_baseline": 0,
                "working_set": n_keys,
                "g_capacity": g_cap,
                "ramp_checks_per_sec": round(n_keys / ramp_s, 1),
                "first_sync_ms": round(first_sync_s * 1e3, 1),
                "first_sync_broadcasts": first_broadcasts,
                "device_collective_us": round(cost_s * 1e6, 1),
                "recommended_sync_wait_ms": round(
                    GlobalManager.window_for_cost(cost_s) * 1e3, 1
                ),
            }
        ),
        flush=True,
    )

    # Over-capacity: a working set LARGER than the gslot table — the
    # replica-table LRU must evict and the sync must stay functional.
    small_cap = max(n_keys // 4, 16)
    over = MeshBucketStore(capacity_per_shard=g_cap, g_capacity=small_cap)
    t0 = time.perf_counter()
    for lo in range(0, n_keys, chunk):
        over.apply(reqs(lo, min(lo + chunk, n_keys)), NOW + lo)
        over.sync_globals(NOW + lo)
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": "cfg7_global_over_capacity_checks_per_sec",
                "value": round(n_keys / dt, 1),
                "unit": "checks/s",
                "vs_baseline": round(n_keys / dt / BASELINE_RPS, 2),
                "working_set": n_keys,
                "g_capacity": small_cap,
                "active_gslots": len(over.gtable.active_gslots()),
            }
        ),
        flush=True,
    )


def config8():
    """Service-path latency distribution through the REAL gateway +
    batcher (round-4 verdict: the p99 < 1ms north star had no direct
    service-path evidence; tunnel numbers measure the tunnel).

    Run with --cpu for the host-path distribution (tunnel-free): single
    -key requests and 1000-lane batches over HTTP against one daemon,
    sequential (latency, not throughput).  On a locally attached chip
    the end-to-end p99 is this host path with the CPU kernel exec
    replaced by the measured on-chip device time (bench.py
    device_us_b1024, ~35-115us) plus PCIe transfer — the decomposition
    the RESULTS.md north-star row reports."""
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon

    def run_edge(native: bool):
        d = Daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                grpc_listen_address="127.0.0.1:0",
                cache_size=16_384,
                peer_discovery_type="static",
                native_http=native or None,
            )
        ).start()
        try:
            d.set_peers([d.peer_info])
            return _config8_measure(d)
        finally:
            d.close()

    stdlib_rows = run_edge(False)
    try:
        native_rows = {f"native_{k}": v for k, v in run_edge(True).items()}
    except RuntimeError:
        native_rows = {"native_edge": "unavailable"}
    print(
        json.dumps(
            {
                "metric": "cfg8_service_latency_1key_p99_ms",
                "value": stdlib_rows["lat_1key_p99_ms"],
                "unit": "ms",
                "vs_baseline": 0,
                **stdlib_rows,
                **native_rows,
                "includes_device_exec": "CPU-backend kernel (swap in "
                "bench.py device_us_b1024 for a locally attached chip)",
            }
        ),
        flush=True,
    )


def _config8_measure(d):
    """One daemon's latency ladder: HTTP 1-key / 1000-lane + in-process
    decomposition rows.  Returns the row dict (caller prints/merges)."""
    import statistics

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.types import (
        Algorithm,
        Behavior,
        GetRateLimitsRequest,
        RateLimitRequest,
    )

    client = V1Client(d.gateway.address, timeout_s=30.0)

    def req(k):
        return RateLimitRequest(
            name="c8", unique_key=k, hits=1, limit=1_000_000,
            duration=3_600_000, algorithm=Algorithm.TOKEN_BUCKET,
        )

    def run(batch_of, n_iters, tag):
        lats = []
        for i in range(max(n_iters // 10, 3)):  # warm
            client.get_rate_limits(batch_of(i))
        for i in range(n_iters):
            b = batch_of(n_iters + i)
            t0 = time.perf_counter()
            client.get_rate_limits(b)
            lats.append((time.perf_counter() - t0) * 1e3)
        lats.sort()
        return {
            f"{tag}_p50_ms": round(lats[len(lats) // 2], 3),
            f"{tag}_p99_ms": round(
                lats[min(len(lats) - 1, int(len(lats) * 0.99))], 3
            ),
            f"{tag}_mean_ms": round(statistics.fmean(lats), 3),
        }

    iters = max(int(200 * SCALE), 20)
    rows = {}
    rows.update(run(lambda i: GetRateLimitsRequest(
        requests=[req(f"one{i % 64}")]), iters, "lat_1key"))
    rows.update(run(lambda i: GetRateLimitsRequest(
        requests=[req(f"k{i % 8}:{j}") for j in range(_sz(1000, lo=16))]),
        max(iters // 4, 10), "lat_1000lane"))

    # Decomposition: in-process service call (no HTTP stack) and
    # NO_BATCHING (no 500us ingress window) — attributes the HTTP
    # p50 to its layers.
    svc = d.service

    def run_inproc(tag, behavior):
        lats = []
        for i in range(iters + 5):
            r = GetRateLimitsRequest(requests=[RateLimitRequest(
                name="c8i", unique_key=f"ip{i % 64}", hits=1,
                limit=1_000_000, duration=3_600_000,
                algorithm=Algorithm.TOKEN_BUCKET, behavior=behavior)])
            t0 = time.perf_counter()
            svc.get_rate_limits(r)
            if i >= 5:
                lats.append((time.perf_counter() - t0) * 1e3)
        lats.sort()
        return {
            f"{tag}_p50_ms": round(lats[len(lats) // 2], 3),
            f"{tag}_p99_ms": round(
                lats[min(len(lats) - 1, int(len(lats) * 0.99))], 3
            ),
        }

    rows.update(run_inproc("lat_inproc_1key", 0))
    rows.update(run_inproc("lat_inproc_nobatch", int(Behavior.NO_BATCHING)))
    return rows


def config9():
    """The reference's own headline bench shape over gRPC
    (BenchmarkServer_ThunderingHeard, benchmark_test.go:109-138): ONE
    shared gRPC client into a cluster daemon, 100 concurrent in-flight
    single-key requests with RANDOM keys — every request creates a
    fresh bucket — at limit 10 / duration 5s / 1 hit.  Single-lane
    requests ride the columnar coalescer (_submit_single_local), so the
    100-way fanout merges into shared pipelined dispatches; the gRPC
    handler pool (128 workers) must not convoy the fanout."""
    import threading as _th

    from gubernator_tpu.client import dial_v1_server, random_string
    from gubernator_tpu.cluster import Cluster, fast_test_behaviors
    from gubernator_tpu.types import GetRateLimitsRequest, RateLimitRequest

    cl = Cluster().start_with([""], behaviors=fast_test_behaviors())
    try:
        client = dial_v1_server(
            cl.daemons[0].peer_info.grpc_address, timeout_s=60.0
        )
        n_fan = 100
        per = max(int(40 * SCALE), 2)

        def req():
            return GetRateLimitsRequest(requests=[RateLimitRequest(
                name="get_rate_limit_benchmark",
                unique_key=random_string(n=10),
                hits=1, limit=10, duration=5_000,
            )])

        lock = _th.Lock()
        totals = [0]
        errs: list = []

        def fan_worker(warm):
            c = 0
            for _ in range(2 if warm else per):
                try:
                    resp = client.get_rate_limits(req())
                    if resp.responses[0].error:
                        raise RuntimeError(resp.responses[0].error)
                    c += 1
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errs.append(e)
            with lock:
                totals[0] += c

        for warm in (True, False):
            if not warm:
                totals[0] = 0
                errs.clear()  # warm-pass hiccups are not timed-run errors
                t0 = time.perf_counter()
            ts = [_th.Thread(target=fan_worker, args=(warm,))
                  for _ in range(n_fan)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        dt = time.perf_counter() - t0
        _emit("9_grpc_thundering_heard", totals[0], dt,
              daemons=1, concurrency=n_fan, keys="random",
              errors=len(errs))
        if errs:
            raise RuntimeError(f"cfg9: {len(errs)} errors, first: {errs[0]}")
    finally:
        cl.stop()


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5,
           6: config6, 7: config7, 8: config8, 9: config9}


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", type=int, choices=sorted(CONFIGS), default=0,
                        help="run one config (default: all)")
    parser.add_argument("--smoke", action="store_true",
                        help="shrink every config ~1000x (correctness/CI)")
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend: tunnel-free host-cost "
                             "and convergence measurements (the TPU rows "
                             "come from the default backend)")
    args = parser.parse_args()
    if args.smoke:
        global SCALE
        SCALE = 0.001

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache_cpu")
    else:
        jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    for n in sorted(CONFIGS) if args.config == 0 else [args.config]:
        CONFIGS[n]()


if __name__ == "__main__":
    main()
