# Reference Makefile:1-35 equivalents for the TPU build.
.PHONY: test tier1 chaos bench bench-gate bench-trend soak soak-smoke soak-regions replay-smoke proto certs docker release clean native

# Compile the C++ host runtime for the CURRENT source of
# gubernator_tpu/native/host_runtime.cpp.  Flags are pinned in ONE
# place (native.CXX_FLAGS) shared with the on-import rebuild, and the
# output is the hash-suffixed `_host_runtime_<sha256[:16]>.so` that
# tests/test_native_build.py requires to match the source in tier-1 —
# after editing the .cpp, run this and commit the fresh .so (deleting
# the superseded one).
native:
	python -c "from gubernator_tpu import native; print(native.build())"

# The whole suite on the virtual 8-device CPU mesh (conftest.py forces
# it); -p no:cacheprovider keeps runs hermetic like -count=1.
test:
	python -m pytest tests/ -q -p no:cacheprovider

# The ROADMAP verify command: fast deterministic tests only.  The
# metrics-name lint runs first (scripts/check_metrics_parity.py):
# reference-parity names are frozen, new names need review there.
tier1:
	env JAX_PLATFORMS=cpu python scripts/check_metrics_parity.py
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Fault-injection suite (pytest.ini `chaos` marker): breaker /
# backoff / degraded-eval behavior under seeded fault plans, the
# resharding scenarios (owner death mid-transfer, DROP/DELAY on
# transfer frames, exactly-once oracle — tests/test_reshard_chaos.py),
# and the durability kill/restart recovery suite (SIGKILL a daemon
# mid-traffic and mid-snapshot-write, restart, assert monotone-bounded
# recovery — tests/test_snapshot_chaos.py), including the slow soaks
# tier-1 skips.
chaos:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos \
		-p no:cacheprovider

# One JSON line: {"metric", "value", "unit", "vs_baseline", ...},
# then the failing regression gate on the stable device rows
# (benchmarks/gate_thresholds.json), then the bench-history trend gate
# (each bench run appends its stamped row to benchmarks/history/;
# scripts/bench_trend.py prints the per-metric trajectory across runs
# — the BENCH_r* seeds included — and fails on a >20% noise-adjusted
# regression vs the rolling same-backend median).
bench:
	python bench.py
	python bench.py --gate
	python scripts/bench_trend.py

# Just the regression gate (reuses rows a bench run saved <1h ago,
# measures fresh otherwise): the one-command CI check.
bench-gate:
	python bench.py --gate

# Just the cross-run trend view/gate over benchmarks/history/.
bench-trend:
	python scripts/bench_trend.py

# The five BASELINE.json configs (one JSON line each); --smoke for CI
bench-full:
	python bench_full.py

# CPU-backend soak smoke: a short long_soak-derived run (slow-marked,
# excluded from tier-1) driving mixed traffic at a 2-daemon cluster
# while polling GET /debug/status and asserting steady-state
# invariants (healthy, breakers closed, no shed, occupancy
# monotone-consistent).  The one-command check of the saturation/SLO
# observability plane; scripts/cluster_status.py renders the same doc.
soak-smoke:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_soak_smoke.py -q \
		-m slow -p no:cacheprovider

# The full cluster soak (ROADMAP item 5's harness): 4 in-process
# daemons under seeded Zipf + burst-replay traffic with FaultPlan
# partitions and membership churn for minutes, trace-sampled, with the
# CONSERVATION AUDIT (audit.py) as the pass/fail gate — exits nonzero
# on any invariant violation (double-commit, lost hits, carry past the
# documented GLOBAL slack, negative remaining).
soak:
	env JAX_PLATFORMS=cpu python scripts/soak.py --minutes 3

# The 2x2 multi-region soak (ISSUE 11's acceptance topology): two
# 2-daemon regions (distinct GUBER_DATA_CENTER), MULTI_REGION lanes
# replicating cross-region through the federation plane
# (federation.py) with the inter-region wire under an always-on
# seeded WAN shape (FaultPlan latency/jitter/loss), WAN storms
# (effective partitions) injected and healed against one region at a
# time, and membership churn rotating WITHIN regions so each region
# reshards independently.  Same audit-silence gate as `make soak`,
# plus the region ledger must have moved (the plane demonstrably ran).
soak-regions:
	env JAX_PLATFORMS=cpu python scripts/soak.py --minutes 3 --regions 2x2

# Incident black box end-to-end in one command (architecture.md
# "Incident black box"): synthesize a capture with a duplicated
# forward frame, write a bundle, replay it TWICE against fresh
# daemons, and require byte-identical reports reproducing the
# forward_conservation violation.  Exits nonzero on any divergence.
replay-smoke:
	env JAX_PLATFORMS=cpu python scripts/replay.py --smoke

proto:
	bash scripts/proto.sh

docker:
	docker build -t gubernator-tpu:latest .

release:
	python -m build --wheel

# Self-signed cluster certs for the TLS compose file / tests
# (reference Makefile:21-34 openssl recipes).
certs:
	mkdir -p certs
	openssl req -x509 -newkey ec -pkeyopt ec_paramgen_curve:P-256 \
		-keyout certs/ca.key -out certs/ca.pem -days 3650 -nodes \
		-subj "/CN=gubernator-tpu CA"
	openssl req -newkey ec -pkeyopt ec_paramgen_curve:P-256 \
		-keyout certs/gubernator.key -out certs/gubernator.csr -nodes \
		-subj "/CN=gubernator"
	openssl x509 -req -in certs/gubernator.csr -CA certs/ca.pem \
		-CAkey certs/ca.key -CAcreateserial -out certs/gubernator.pem \
		-days 3650 \
		-extfile <(printf "subjectAltName=DNS:gubernator-1,DNS:gubernator-2,DNS:localhost,IP:127.0.0.1")
	rm -f certs/gubernator.csr certs/ca.srl

clean:
	rm -rf build dist *.egg-info .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
