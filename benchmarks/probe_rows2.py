"""Round-3 probe #6: confirm row-scatter wins at production capacity."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

B = 131_072
K1, K2 = 4, 20

rng = np.random.RandomState(7)
_ = np.asarray(jnp.zeros((1,), jnp.int32))


def first_leaf(tree):
    return jax.tree_util.tree_leaves(tree)[0]


def bench(name, make_run, *args):
    runs = {k: make_run(k) for k in (K1, K2)}
    ts = {}
    for k, fn in runs.items():
        out = fn(*args)
        np.asarray(first_leaf(out).ravel()[:1])
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(first_leaf(out).ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        ts[k] = best
    c = (ts[K2] - ts[K1]) / (K2 - K1)
    print(f"{name:44s} {c*1e6:10.1f} us/iter", flush=True)
    return c


def chain(body, K):
    @jax.jit
    def run(state, *rest):
        def f(i, st):
            return body(st, i, *rest)

        return jax.lax.fori_loop(0, K, f, state)

    return run


def rmw_rows(st, i, ix):
    g = st[ix]
    return st.at[ix].set(g + 1, mode="drop", unique_indices=True)


def main():
    for C in (262_144, 2_097_152):
        idx = np.sort(rng.choice(C, size=B, replace=False).astype(np.int32))
        idx = jnp.asarray(idx)
        rows = jnp.asarray(rng.randint(0, 1 << 20, size=(C, 16), dtype=np.int32))
        bench(f"rmw rows [{C},16] sorted", lambda K: chain(rmw_rows, K), rows, idx)
        del rows

    C = 262_144
    idxs = np.sort(rng.choice(C, size=B, replace=False).astype(np.int32))
    idx = jnp.asarray(idxs)

    rows8 = jnp.asarray(rng.randint(0, 1 << 20, size=(C, 8), dtype=np.int32))

    def rmw2(st, i, ix):
        a, b = st
        return (
            a.at[ix].set(a[ix] + 1, mode="drop", unique_indices=True),
            b.at[ix].set(b[ix] + 1, mode="drop", unique_indices=True),
        )

    bench("rmw 2x rows [C,8] sorted", lambda K: chain(rmw2, K), (rows8, rows8 + 1), idx)

    # gather rows honest (random idx), fold into carry
    ridx = jnp.asarray(rng.choice(C, size=B, replace=False).astype(np.int32))
    rows = jnp.asarray(rng.randint(0, 1 << 20, size=(C, 16), dtype=np.int32))

    def gath_rows(carry, i, st, ix):
        return carry + st[ix + (carry[0, 0] & 0)]

    bench("gather rows [C,16] random", lambda K: chain(gath_rows, K),
          jnp.zeros((B, 16), jnp.int32), rows, ridx)

    # in-batch argsort+permute+scatter end-to-end (unsorted input slots)
    def full_commit(st, i, ix):
        g = st[ix]  # gather random
        perm = jnp.argsort(ix)
        return st.at[ix[perm]].set(g[perm] + 1, mode="drop", unique_indices=True)

    bench("gather+argsort+perm+scatter [C,16]", lambda K: chain(full_commit, K), rows, ridx)


if __name__ == "__main__":
    main()
