"""Instrumented cfg5 repro: WHERE does the 100-way MULTI_REGION storm
spend its time?  (VERDICT r4: 1,217 checks/s = 0.6x baseline, the one
losing number.)

Counts device dispatches, peer RPCs, error lanes, and CPU vs wall time
for the storm epoch.  Run on the tunnel chip (default) or --cpu.
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--clients", type=int, default=100)
    ap.add_argument("--batch", type=int, default=512)
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache_cpu")
    else:
        jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from gubernator_tpu.client import V1Client
    from gubernator_tpu.cluster import Cluster
    from gubernator_tpu.parallel.mesh import MeshBucketStore
    from gubernator_tpu.peer_client import PeerClient
    from gubernator_tpu.types import (
        Algorithm,
        Behavior,
        GetRateLimitsRequest,
        RateLimitRequest,
    )

    counters = {
        "dispatch_columns": 0,
        "dispatch_lanes": 0,
        "apply_dataclass": 0,
        "peer_rpcs": 0,
        "peer_rpc_lanes": 0,
        "peer_rpc_time_s": 0.0,
    }
    clock = {"on": False}
    lk = threading.Lock()

    orig_async = MeshBucketStore.apply_columns_async
    orig_apply = MeshBucketStore.apply
    orig_rpc = PeerClient.get_peer_rate_limits

    def wrap_async(self, keys, *a, **kw):
        if clock["on"]:
            with lk:
                counters["dispatch_columns"] += 1
                counters["dispatch_lanes"] += len(keys)
        return orig_async(self, keys, *a, **kw)

    def wrap_apply(self, reqs, *a, **kw):
        if clock["on"]:
            with lk:
                counters["apply_dataclass"] += 1
                counters["dispatch_lanes"] += len(reqs)
        return orig_apply(self, reqs, *a, **kw)

    def wrap_rpc(self, req, *a, **kw):
        t0 = time.perf_counter()
        try:
            return orig_rpc(self, req, *a, **kw)
        finally:
            if clock["on"]:
                with lk:
                    counters["peer_rpcs"] += 1
                    counters["peer_rpc_lanes"] += len(req.requests)
                    counters["peer_rpc_time_s"] += time.perf_counter() - t0

    MeshBucketStore.apply_columns_async = wrap_async
    MeshBucketStore.apply = wrap_apply
    PeerClient.get_peer_rate_limits = wrap_rpc

    from gubernator_tpu.cluster import fast_test_behaviors

    beh = fast_test_behaviors()
    beh.batch_timeout_s = 30.0
    cl = Cluster().start_with(["", "", "dc-east", "dc-east"], behaviors=beh)
    try:
        clients = [V1Client(d.gateway.address, timeout_s=120.0) for d in cl.daemons]
        rng = np.random.RandomState(5)
        batches = []
        for _ in range(8):
            batches.append(
                GetRateLimitsRequest(
                    requests=[
                        RateLimitRequest(
                            name="c5",
                            unique_key=f"storm{rng.randint(16)}",
                            hits=5,
                            limit=10,
                            duration=60_000,
                            algorithm=Algorithm.TOKEN_BUCKET,
                            behavior=Behavior.MULTI_REGION,
                        )
                        for _ in range(args.batch)
                    ]
                )
            )
        for c in clients:
            c.get_rate_limits(batches[0])

        N = args.clients
        totals = [0, 0, 0]  # responses, over_limit, errors
        lats = []
        tlock = threading.Lock()

        err_samples = {}

        def _storm(i, b):
            t0 = time.perf_counter()
            resp = clients[i % len(clients)].get_rate_limits(b)
            dt = time.perf_counter() - t0
            o = sum(r.status == 1 for r in resp.responses)
            e = 0
            for r in resp.responses:
                if r.error:
                    e += 1
                    with tlock:
                        key = r.error[:120]
                        err_samples[key] = err_samples.get(key, 0) + 1
            with tlock:
                totals[0] += len(resp.responses)
                totals[1] += o
                totals[2] += e
                lats.append(dt)

        warm = [
            threading.Thread(target=_storm, args=(i, batches[i % len(batches)]))
            for i in range(N)
        ]
        for t in warm:
            t.start()
        for t in warm:
            t.join()
        totals[0] = totals[1] = totals[2] = 0
        lats.clear()

        clock["on"] = True
        cpu0 = time.process_time()
        t0 = time.perf_counter()
        ts = [
            threading.Thread(target=_storm, args=(i, batches[i % len(batches)]))
            for i in range(N)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        cpu = time.process_time() - cpu0
        clock["on"] = False

        lats.sort()
        print(
            json.dumps(
                {
                    "checks_per_sec": round(totals[0] / wall, 1),
                    "wall_s": round(wall, 2),
                    "process_cpu_s": round(cpu, 2),
                    "responses": totals[0],
                    "over_limit": totals[1],
                    "error_lanes": totals[2],
                    "storm_lat_s_p50": round(lats[len(lats) // 2], 2),
                    "storm_lat_s_max": round(lats[-1], 2),
                    **{k: (round(v, 2) if isinstance(v, float) else v)
                       for k, v in counters.items()},
                    "error_kinds": dict(
                        sorted(err_samples.items(), key=lambda kv: -kv[1])[:6]
                    ),
                },
                indent=1,
            ),
            flush=True,
        )
    finally:
        cl.stop()


if __name__ == "__main__":
    main()
