"""Round-4 measurement: mesh scaling shape at 1/2/4/8 shards.

The round-3 north-star claim multiplied one chip's device rate by 8 —
an unmeasured projection (VERDICT r3).  Real multi-chip hardware is not
available here, but the virtual CPU mesh runs REAL sharded programs
(one fused dispatch over S devices; real psum collectives in the GLOBAL
sync), so the SCALING SHAPE — how fixed total work behaves as the shard
count grows — is measurable.  Absolute numbers are CPU-bound and mean
nothing vs the TPU rows; the ratio columns are the result.

For S in {1, 2, 4, 8}: one child process pinned to S virtual devices
(xla_force_host_platform_device_count, exactly how tests/conftest.py
provisions the suite) runs

  * columnar ingress: the SAME fixed workload (131072-lane Zipf batch
    over 100k keys, mixed token+leaky, 262144 total slots split over
    the shards) through MeshBucketStore.apply_columns_async, depth-1
    pipelined, best-of-3 epochs; and
  * GLOBAL sync: measure_sync_cost_s on a 512-gslot table (64 active
    keys), the collective whose cost sets the GlobalSyncWait window.

Usage:
    python benchmarks/mesh_scaling.py          # parent: all S, table
    python benchmarks/mesh_scaling.py --child S  # one measurement
"""

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

B = 131_072
N_KEYS = 100_000
TOTAL_SLOTS = 262_144
NOW = 1_700_000_000_000


def child(S: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache_cpu"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import numpy as np

    from gubernator_tpu.parallel.mesh import MeshBucketStore, make_mesh

    devices = jax.devices()[:S]
    assert len(devices) == S, (S, jax.devices())
    mesh = make_mesh(devices)
    store = MeshBucketStore(
        capacity_per_shard=TOTAL_SLOTS // S, g_capacity=512, mesh=mesh
    )

    rng = np.random.RandomState(42)
    hot = rng.randint(0, N_KEYS // 10, size=B)
    cold = rng.randint(0, N_KEYS, size=B)
    key_ids = np.where(rng.random(B) < 0.8, hot, cold)
    keys = [f"scale_account:{k}" for k in key_ids]
    algo = (key_ids % 2).astype(np.int32)
    behavior = np.zeros(B, np.int32)
    hits = np.ones(B, np.int64)
    limit = np.full(B, 1_000_000, np.int64)
    duration = np.full(B, 3_600_000, np.int64)

    def pump(ks, al, bh, ht, lm, dr, nb):
        def dispatch(i):
            return store.apply_columns_async(
                ks, al, bh, ht, lm, dr, NOW + i
            )

        dispatch(0).result()  # compile + fill
        dispatch(1).result()
        iters, best = 4, 0.0
        step = 2
        for _ in range(3):
            t0 = time.perf_counter()
            pending = None
            for i in range(iters):
                h = dispatch(step + i)
                if pending is not None:
                    pending.result()
                pending = h
            pending.result()
            dt = time.perf_counter() - t0
            step += iters
            best = max(best, nb * iters / dt)
        return best

    best = pump(keys, algo, behavior, hits, limit, duration, B)

    # Weak scaling: per-shard work CONSTANT (16384 lanes x S), so a
    # flat per-batch time across S means the fused program really runs
    # the shards concurrently.
    BW = 16_384 * S
    wk_ids = key_ids[:BW]
    weak = pump(
        [f"scale_account:{k}" for k in wk_ids],
        (wk_ids % 2).astype(np.int32), np.zeros(BW, np.int32),
        np.ones(BW, np.int64), np.full(BW, 1_000_000, np.int64),
        np.full(BW, 3_600_000, np.int64), BW,
    )

    # GLOBAL sync collective cost on a fresh store (measure_sync_cost_s
    # refuses live GLOBAL traffic).
    gstore = MeshBucketStore(
        capacity_per_shard=4096, g_capacity=512, mesh=mesh
    )
    from gubernator_tpu.types import Behavior, RateLimitRequest

    for i in range(64):
        gstore.apply(
            [
                RateLimitRequest(
                    name="gs", unique_key=f"g{i}", hits=1, limit=1000,
                    duration=60_000, behavior=Behavior.GLOBAL,
                )
            ],
            NOW,
        )
    gstore.sync_globals(NOW + 1)
    # measure raw sync cost via the same chained method the store's
    # tuner uses, but on this store WITH its 64 live keys: time real
    # sync_globals passes (host legs included — the serving cost).
    t0 = time.perf_counter()
    n_sync = 10
    for i in range(n_sync):
        gstore.sync_globals(NOW + 2 + i)
    sync_s = (time.perf_counter() - t0) / n_sync

    print(json.dumps({
        "S": S, "columnar_cps": best, "weak_cps": weak,
        "sync_ms": sync_s * 1e3,
    }))


def main() -> None:
    if len(sys.argv) > 2 and sys.argv[1] == "--child":
        child(int(sys.argv[2]))
        return
    rows = []
    for S in (1, 2, 4, 8):
        env = dict(os.environ)
        xla = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            env.get("XLA_FLAGS", ""),
        )
        env["XLA_FLAGS"] = f"{xla} --xla_force_host_platform_device_count={S}".strip()
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", str(S)],
            env=env, cwd=REPO, check=True, capture_output=True, text=True,
            timeout=1800,
        )
        line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
        rows.append(json.loads(line))
        print(line, flush=True)
    base = rows[0]
    print(f"\n{'S':>2} {'fixed-work cps':>15} {'vs S=1':>7} "
          f"{'weak cps':>12} {'vs S=1':>7} {'sync ms':>8} {'vs S=1':>7}")
    for r in rows:
        print(
            f"{r['S']:>2} {r['columnar_cps']:>15,.0f} "
            f"{r['columnar_cps'] / base['columnar_cps']:>6.2f}x "
            f"{r['weak_cps']:>12,.0f} "
            f"{r['weak_cps'] / base['weak_cps']:>6.2f}x "
            f"{r['sync_ms']:>8.2f} {r['sync_ms'] / base['sync_ms']:>6.2f}x"
        )


if __name__ == "__main__":
    main()
