"""Device probes for the bucket-kernel commit path redesign (round 3).

Measures, on the real chip, the primitive costs that decide the fused
kernel design: XLA gather vs scatter per-element cost, scatter variants
(column/row/sorted/unique), and Pallas dynamic-index feasibility.

Each probe chains ITERS dependent iterations inside one jit so the
tunnel RTT amortizes; reported number is device time per iteration.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

C = 2_000_000
B = 131_072
ITERS = 8
N_COLS = 11

rng = np.random.RandomState(7)
idx_np = rng.choice(C, size=B, replace=False).astype(np.int32)
idx_sorted_np = np.sort(idx_np)
vals_np = rng.randint(0, 1 << 30, size=(B,), dtype=np.int32)


def bench(name, fn, *args, **extra):
    out = jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / ITERS
    del out
    print(f"{name:42s} {dt*1e6:10.1f} us/iter  {extra}")
    return dt


def chain(body):
    """jit a fori_loop that chains `body(state, i) -> state` ITERS times."""

    @jax.jit
    def run(state, *rest):
        def f(i, st):
            return body(st, i, *rest)

        return jax.lax.fori_loop(0, ITERS, f, state)

    return run


def main():
    cols = [jnp.zeros((C,), jnp.int32) for _ in range(N_COLS)]
    idx = jnp.asarray(idx_np)
    idx_sorted = jnp.asarray(idx_sorted_np)
    vals = jnp.asarray(vals_np)

    # --- elementwise pass over the batch (compute-ish floor) ---
    def ew(st, i):
        return [c + 1 for c in st]

    bench("elementwise 11 cols full table", chain(ew), cols)

    # --- gather: 11 columns at B random indices ---
    def gath(st, i, ix):
        acc = jnp.zeros((B,), jnp.int32)
        for c in st:
            acc = acc + c[ix]
        return [st[0].at[0].set(acc[0])] + st[1:]

    bench("gather 11 cols x131k random", chain(gath), cols, idx)

    # --- scatter variants ---
    def scat_cols(st, i, ix, v):
        return [c.at[ix].set(v + i, mode="drop") for c in st]

    bench("scatter 11 cols x131k random", chain(scat_cols), cols, idx, vals)

    def scat_cols_u(st, i, ix, v):
        return [
            c.at[ix].set(v + i, mode="drop", unique_indices=True) for c in st
        ]

    bench("scatter 11 cols unique_indices", chain(scat_cols_u), cols, idx, vals)
    bench("scatter 11 cols sorted+unique", chain(scat_cols_u), cols, idx_sorted, vals)

    # --- row-major state: one scatter of [B,16] rows ---
    rows_state = jnp.zeros((C, 16), jnp.int32)
    row_vals = jnp.zeros((B, 16), jnp.int32)

    def scat_rows(st, i, ix, v):
        return st.at[ix].set(v + i, mode="drop", unique_indices=True)

    bench("scatter rows [C,16] unique", chain(scat_rows), rows_state, idx, row_vals)
    bench("scatter rows [C,16] sorted", chain(scat_rows), rows_state, idx_sorted, row_vals)

    rows8 = jnp.zeros((C, 8), jnp.int32)
    rv8 = jnp.zeros((B, 8), jnp.int32)
    bench("scatter rows [C,8] unique", chain(scat_rows), rows8, idx, rv8)

    rows128 = jnp.zeros((C // 8, 128), jnp.int32)
    rv128 = jnp.zeros((B, 128), jnp.int32)
    idx8 = jnp.asarray(idx_np % (C // 8))
    bench("scatter rows [C/8,128] unique", chain(scat_rows), rows128, idx8, rv128)

    def gath_rows(st, i, ix):
        g = st[ix]
        return st.at[0, 0].set(g[0, 0] + i)

    bench("gather rows [C,16] x131k", chain(gath_rows), rows_state, idx)

    # --- on-device sort cost (for slot-sorted scatter) ---
    def sortcost(st, i, v):
        s = jnp.sort(v + i)
        return st.at[0].set(s[0], mode="drop")

    bench("sort 131k i32", chain(sortcost), cols[0], idx)

    def argsortcost(st, i, v):
        s = jnp.argsort(v + i)
        return st.at[0].set(s[0].astype(jnp.int32), mode="drop")

    bench("argsort 131k i32", chain(argsortcost), cols[0], idx)


if __name__ == "__main__":
    main()
