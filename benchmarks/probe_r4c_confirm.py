"""Round-4 probe #4: confirm the narrow-vs-wide gap at higher resolution.

probe_r4_bisect measured with dK=16, whose tunnel-weather error bar is
~±1.5ms/batch — enough to invert fine-grained variants (it put the wide
kernel BELOW the scatter-alone floor, impossible).  This probe re-runs
the three numbers that matter with dK=64 (error ~±0.4ms) and verifies
against dead-code elimination by checking the chained state actually
mutated (token remaining must drop by exactly K).

  A  apply_rounds32 (production narrow)
  B  apply_rounds   (wide)
  S  hot-row rmw scatter (floor)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from gubernator_tpu.ops import buckets

B = 131_072
C = 262_144
K_LO, K_HI = 4, 68
NOW = 1_700_000_000_000

rng = np.random.RandomState(7)
_ = np.asarray(jnp.zeros((1,), jnp.int32))

_I64 = jnp.int64


def measure(name, make_fn, state, *args, check=None):
    ts = {}
    for K in (K_LO, K_HI):
        fn = make_fn(K)
        st, out = fn(state, *args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        if check is not None:
            check(K, st)
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            st, out = fn(st, *args)
            np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        ts[K] = best
        del st, out
    us = (ts[K_HI] - ts[K_LO]) / (K_HI - K_LO) * 1e6
    print(f"{name:44s} {us:9.1f} us/batch "
          f"(t{K_LO}={ts[K_LO]*1e3:.1f}ms t{K_HI}={ts[K_HI]*1e3:.1f}ms)",
          flush=True)
    return us


def chain(body):
    def make(K):
        @jax.jit
        def run(state, *args):
            def f(i, c):
                st, _ = c
                st, out = body(st, i, *args)
                return jax.lax.optimization_barrier((st, out))

            st0, out0 = body(state, jnp.asarray(0, jnp.int32), *args)
            return jax.lax.fori_loop(1, K, f, (st0, out0))

        return run

    return make


def main():
    one = jnp.asarray(1, jnp.int32)
    slot = rng.permutation(C)[:B].astype(np.int32)
    n = B
    big = 1 << 30
    b32 = jax.device_put(buckets.make_batch32(
        slot, np.ones(n, bool), np.zeros(n, np.int32),  # all token
        np.zeros(n, np.int32), np.ones(n, np.int32),
        np.full(n, big, np.int32), np.full(n, 3_600_000, np.int32),
    ))
    b64 = jax.device_put(buckets.make_batch(
        slot, np.ones(n, bool), np.zeros(n, np.int32),
        np.zeros(n, np.int32), np.ones(n, np.int64),
        np.full(n, big, np.int64), np.full(n, 3_600_000, np.int64),
    ))
    rid = jax.device_put(np.zeros(n, np.int32))

    state0 = buckets.init_state(C)
    create = jax.device_put(
        buckets.make_batch(
            slot, np.zeros(n, bool), np.zeros(n, np.int32),
            np.zeros(n, np.int32), np.zeros(n, np.int64),  # hits=0: full
            np.full(n, big, np.int64), np.full(n, 3_600_000, np.int64),
        )
    )
    state0, _p = buckets.apply_rounds_jit(state0, create, rid, one, NOW)
    np.asarray(_p[:1, :1])
    now_dev = jnp.asarray(NOW, _I64)

    probe_slot = int(slot[12345])

    def expect_drop(K, st):
        # Token remaining for a probed slot must have dropped by exactly
        # the number of chained batches — proof nothing was DCE'd.
        rows = buckets.read_rows(st, np.array([probe_slot], np.int32))
        rem = int(np.asarray(rows.remaining)[0])
        drop = big - rem
        assert drop % K == 0 and drop > 0, (K, rem, drop)

    def a_body(st, i, b, r):
        return buckets.apply_rounds32(st, b, r, one, now_dev + i.astype(_I64))

    measure("A apply_rounds32 narrow", chain(a_body), state0, b32, rid,
            check=expect_drop)

    def b_body(st, i, b, r):
        return buckets.apply_rounds(st, b, r, one, now_dev + i.astype(_I64))

    measure("B apply_rounds wide", chain(b_body), state0, b64, rid,
            check=expect_drop)

    def s_body(st, i, ix):
        g = st.hot[ix]
        return st._replace(
            hot=st.hot.at[ix].set(g + 1, mode="drop", unique_indices=True)
        ), g[:1]

    measure("S rmw hot-row scatter floor", chain(s_body), state0,
            jnp.asarray(slot))


if __name__ == "__main__":
    main()
