"""Round-3 probe #5: per-index vs per-element scatter cost (honest mode).

Decides the state layout: 11 i32 columns (current) vs row-major
[C,16]/[C,128].  Also: gather vs scatter split, sorted indices, and
on-device sort cost.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gubernator_tpu  # noqa: F401  (x64)
import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

C = 262_144
B = 131_072
K1, K2 = 4, 20

rng = np.random.RandomState(7)
idx_np = rng.choice(C, size=B, replace=False).astype(np.int32)
idx_sorted_np = np.sort(idx_np)

_ = np.asarray(jnp.zeros((1,), jnp.int32))  # honest mode


def first_leaf(tree):
    return jax.tree_util.tree_leaves(tree)[0]


def bench(name, make_run, *args):
    runs = {k: make_run(k) for k in (K1, K2)}
    ts = {}
    for k, fn in runs.items():
        out = fn(*args)
        np.asarray(first_leaf(out).ravel()[:1])
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(first_leaf(out).ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        ts[k] = best
    c = (ts[K2] - ts[K1]) / (K2 - K1)
    print(f"{name:40s} {c*1e6:10.1f} us/iter", flush=True)
    return c


def chain(body, K):
    @jax.jit
    def run(state, *rest):
        def f(i, st):
            return body(st, i, *rest)

        return jax.lax.fori_loop(0, K, f, state)

    return run


def main():
    cols = [
        jnp.asarray(rng.randint(0, 1 << 20, size=C, dtype=np.int32))
        for _ in range(11)
    ]
    idx = jnp.asarray(idx_np)
    idx_s = jnp.asarray(idx_sorted_np)

    # gather-only: fold gathers into a B-sized carry
    def gath(carry, i, st, ix):
        acc = carry
        for c in st:
            acc = acc + c[ix + (i & 0)]
        return acc

    bench("gather-only 11 cols", lambda K: chain(gath, K), jnp.zeros((B,), jnp.int32), cols, idx)
    bench("gather-only 11 cols sorted", lambda K: chain(gath, K), jnp.zeros((B,), jnp.int32), cols, idx_s)

    # scatter-only: values derived from carry scalar to defeat DCE-free motion
    def scat(st, i, ix):
        v = st[0][0] + jnp.int32(1)
        return [c.at[ix].set(v, mode="drop", unique_indices=True) for c in st]

    bench("scatter-only 11 cols", lambda K: chain(scat, K), cols, idx)
    bench("scatter-only 11 cols sorted", lambda K: chain(scat, K), cols, idx_s)

    def rmw_cols(st, i, ix):
        gs = [c[ix] for c in st]
        return [
            c.at[ix].set(g + 1, mode="drop", unique_indices=True)
            for c, g in zip(st, gs)
        ]

    bench("rmw 11 cols sorted", lambda K: chain(rmw_cols, K), cols, idx_s)

    # row-major
    for W in (16, 128):
        rows = jnp.asarray(rng.randint(0, 1 << 20, size=(C, W), dtype=np.int32))

        def rmw_rows(st, i, ix):
            g = st[ix]
            return st.at[ix].set(g + 1, mode="drop", unique_indices=True)

        bench(f"rmw rows [C,{W}] random", lambda K: chain(rmw_rows, K), rows, idx)
        bench(f"rmw rows [C,{W}] sorted", lambda K: chain(rmw_rows, K), rows, idx_s)
        del rows

    # 8-col-packed rows: [C, 8] (one 32B row per slot)
    rows8 = jnp.asarray(rng.randint(0, 1 << 20, size=(C, 8), dtype=np.int32))

    def rmw_rows8(st, i, ix):
        g = st[ix]
        return st.at[ix].set(g + 1, mode="drop", unique_indices=True)

    bench("rmw rows [C,8] random", lambda K: chain(rmw_rows8, K), rows8, idx)

    # on-device sort / argsort of the slot column
    def sortb(carry, i, v):
        return jnp.sort(v + carry[0]).astype(jnp.int32)

    bench("sort 131k i32", lambda K: chain(sortb, K), idx, idx)

    def argsortb(carry, i, v):
        return jnp.argsort(v + carry[0]).astype(jnp.int32)

    bench("argsort 131k i32", lambda K: chain(argsortb, K), idx, idx)


if __name__ == "__main__":
    main()
