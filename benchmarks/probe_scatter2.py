"""Round-3 probe #2: DCE-proof device costs.

Every body is a gather->modify->scatter chain on the same state, so no
iteration can be elided; all ITERS run inside ONE jit dispatch so the
tunnel's per-dispatch cost is excluded.  Cross-checks bench.py's 32ms
"device_batch_us" (which pays one tunnel enqueue per batch).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

C = 262_144
B = 131_072
ITERS = 16
N_COLS = 11

rng = np.random.RandomState(7)
idx_np = rng.choice(C, size=B, replace=False).astype(np.int32)


def bench(name, fn, *args):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / ITERS
    del out
    print(f"{name:44s} {dt*1e6:10.1f} us/iter", flush=True)
    return dt


def chain(body):
    @jax.jit
    def run(state, *rest):
        def f(i, st):
            return body(st, i, *rest)

        return jax.lax.fori_loop(0, ITERS, f, state)

    return run


def main():
    cols = [
        jnp.asarray(rng.randint(0, 1 << 20, size=C, dtype=np.int32))
        for _ in range(N_COLS)
    ]
    idx = jnp.asarray(idx_np)

    # rmw: gather all 11, add, scatter all 11 (the commit path shape)
    def rmw_cols(st, i, ix):
        gs = [c[ix] for c in st]
        return [
            c.at[ix].set(g + 1, mode="drop", unique_indices=True)
            for c, g in zip(st, gs)
        ]

    bench("rmw 11 cols gather+scatter", chain(rmw_cols), cols, idx)

    # same but only 4 columns scattered (hot-column variant)
    def rmw_cols4(st, i, ix):
        gs = [c[ix] for c in st]
        upd = [
            c.at[ix].set(g + 1, mode="drop", unique_indices=True)
            for c, g in zip(st[:4], gs[:4])
        ]
        return upd + [c + g[0] * 0 for c, g in zip(st[4:], gs[4:])]

    bench("rmw gather 11 / scatter 4 cols", chain(rmw_cols4), cols, idx)

    # row-major [C,16]
    rows = jnp.asarray(rng.randint(0, 1 << 20, size=(C, 16), dtype=np.int32))

    def rmw_rows(st, i, ix):
        g = st[ix]
        return st.at[ix].set(g + 1, mode="drop", unique_indices=True)

    bench("rmw rows [C,16]", chain(rmw_rows), rows, idx)

    # full-table elementwise (bandwidth sanity: 11 cols r+w)
    def ew(st, i, ix):
        return [c + jnp.int32(i) for c in st]

    bench("elementwise 11 cols full table", chain(ew), cols, idx)

    # the real kernel, chained in one jit
    from gubernator_tpu.ops import buckets

    state = buckets.init_state(C)
    slot = np.arange(B, dtype=np.int32)
    b32 = buckets.make_batch32(
        slot,
        np.ones(B, dtype=bool),
        (slot % 2).astype(np.int32),
        np.zeros(B, np.int32),
        np.ones(B, np.int32),
        np.full(B, 1 << 30, np.int32),
        np.full(B, 3_600_000, np.int32),
    )
    rid = jnp.zeros(B, jnp.int32)
    now0 = jnp.int64(1_700_000_000_000)

    @jax.jit
    def kern_chain(st, req, rid):
        def f(i, c):
            st, _ = c
            st, packed = buckets.apply_rounds32(
                st, req, rid, jnp.int32(1), now0 + i.astype(jnp.int64)
            )
            return jax.lax.optimization_barrier((st, packed))

        B = req.slot.shape[0]
        return jax.lax.fori_loop(0, ITERS, f, (st, jnp.zeros((4, B), jnp.int32)))

    # create buckets first
    create = b32._replace(exists=jnp.zeros(B, bool))
    state, _ = buckets.apply_rounds32_jit(state, create, rid, jnp.int32(1), now0)
    bench("apply_rounds32 in-jit chain", kern_chain, state, b32, rid)

    # per-dispatch enqueue cost over the tunnel (bench.py methodology)
    state2 = buckets.init_state(C)
    state2, packed = buckets.apply_rounds32_jit(state2, create, rid, jnp.int32(1), now0)
    np.asarray(packed[0, :1])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state2, packed = buckets.apply_rounds32_jit(
            state2, b32, rid, jnp.int32(1), now0
        )
    np.asarray(packed[0, :1])
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{'apply_rounds32 per-dispatch (tunnel)':44s} {dt*1e6:10.1f} us/iter")


if __name__ == "__main__":
    main()
