"""Round-5 probe: does scatter ROW WIDTH price the hot-row commit?

Round 4 established the scatter floor (131k-row RMW into [262k, 8] i32
~2.75 ms) and killed masking/compaction/sorting as levers.  Width was
never isolated — the only datapoint is [C,16] costing ~6x [C,8] at 2M
slots, which suggests a steep width curve.  If [C,4] RMW is ~2x
cheaper, splitting the hot row (flags/remaining/expire in [C,4];
stamp+rem_hi in a second [C,4] written only by leaky/wide lanes) beats
the current single [C,8] on mixed traffic and wins ~big on token-only
traffic.

Differential dK chaining (K=4 vs 68) so tunnel RTT cancels; every
variant's chained state is mutation-checked against DCE.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

C = 262_144
B = 131_072
K_LO, K_HI = 4, 68
SAMPLES = 5

rng = np.random.RandomState(7)
idx_np = rng.choice(C, size=B, replace=False).astype(np.int32)
# "leaky half": every other write lane also hits the aux table
aux_idx_np = np.where(np.arange(B) % 2 == 0, idx_np, C + 10).astype(np.int32)

_ = np.asarray(jnp.zeros((1,), jnp.int32))  # honest-timing mode


def chain(body, K):
    @jax.jit
    def run(state, idx, aux_idx):
        def f(i, st):
            return jax.lax.optimization_barrier(body(st, i, idx, aux_idx))

        return jax.lax.fori_loop(0, K, f, state)

    return run


def measure(name, body, state0, check=None):
    ts = {}
    for K in (K_LO, K_HI):
        fn = chain(body, K)
        st = fn(state0, jnp.asarray(idx_np), jnp.asarray(aux_idx_np))
        np.asarray(jax.tree_util.tree_leaves(st)[0].ravel()[:1])  # drain
        best = float("inf")
        for _ in range(SAMPLES):
            t0 = time.perf_counter()
            st = fn(st, jnp.asarray(idx_np), jnp.asarray(aux_idx_np))
            np.asarray(jax.tree_util.tree_leaves(st)[0].ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        ts[K] = best
        if check is not None:
            check(st, K)
    us = (ts[K_HI] - ts[K_LO]) / (K_HI - K_LO) * 1e6
    print(f"{name:44s} {us:9.1f} us/batch", flush=True)
    return us


def rmw_width(width):
    def body(st, i, idx, aux_idx):
        rows = st[idx]
        rows = rows + 1
        return st.at[idx].set(rows, mode="drop")

    return body


def rmw_split(st, i, idx, aux_idx):
    t1, t2 = st
    r1 = t1[idx] + 1
    t1 = t1.at[idx].set(r1, mode="drop")
    r2 = t2[jnp.clip(aux_idx, 0, C - 1)] + 1
    t2 = t2.at[aux_idx].set(r2, mode="drop")
    return (t1, t2)


def main():
    for width in (8, 4, 2):
        st = jnp.zeros((C, width), jnp.int32)

        def check(s, K, w=width):
            # DCE check: every indexed row must have advanced by K per run
            v = int(np.asarray(s[idx_np[0], 0]))
            assert v > 0, (w, v)

        measure(f"rmw [{C},{width}] 131k rows", rmw_width(width), st, check)

    st2 = (jnp.zeros((C, 4), jnp.int32), jnp.zeros((C, 4), jnp.int32))
    measure("split: rmw [C,4] all + [C,4] half", rmw_split, st2)

    # Width at the 2M single-table size (the table-size term interacts
    # with width; two-tier made 262k the production front, but record
    # the curve).
    C2 = 2_097_152
    for width in (8, 4):
        st = jnp.zeros((C2, width), jnp.int32)

        def body(s, i, idx, aux_idx):
            rows = s[idx] + 1
            return s.at[idx].set(rows, mode="drop")

        ts = {}
        for K in (K_LO, K_HI):
            fn = chain(body, K)
            s = fn(st, jnp.asarray(idx_np), jnp.asarray(aux_idx_np))
            np.asarray(s.ravel()[:1])
            best = float("inf")
            for _ in range(SAMPLES):
                t0 = time.perf_counter()
                s = fn(s, jnp.asarray(idx_np), jnp.asarray(aux_idx_np))
                np.asarray(s.ravel()[:1])
                best = min(best, time.perf_counter() - t0)
            ts[K] = best
        us = (ts[K_HI] - ts[K_LO]) / (K_HI - K_LO) * 1e6
        print(f"rmw [2M,{width}] 131k rows {us:31.1f} us/batch", flush=True)


if __name__ == "__main__":
    main()
