"""Round-3 probe #4: honest-mode re-measurement of everything.

Gotcha (see bench.py): until the process performs one real device->host
readback, block_until_ready returns optimistically — timings are fake.
So: (1) flip into honest mode with an early readback, (2) every timed
region ends in a 1-element readback, (3) per-iteration cost comes from
the difference between a K2-iteration and K1-iteration in-jit chain so
the tunnel RTT and fixed overheads cancel.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gubernator_tpu  # noqa: F401  (enables x64)
import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

C = 262_144
B = 131_072
K1, K2 = 4, 20

rng = np.random.RandomState(7)
idx_np = rng.choice(C, size=B, replace=False).astype(np.int32)

# flip into honest mode
_ = np.asarray(jnp.zeros((1,), jnp.int32))


def first_leaf(tree):
    return jax.tree_util.tree_leaves(tree)[0]


def bench(name, make_run, *args):
    """make_run(K) -> jitted fn(*args) returning a tree; reads back 1 elt."""
    runs = {k: make_run(k) for k in (K1, K2)}
    ts = {}
    for k, fn in runs.items():
        out = fn(*args)
        np.asarray(first_leaf(out).ravel()[:1])  # warm/compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(first_leaf(out).ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        ts[k] = best
    c = (ts[K2] - ts[K1]) / (K2 - K1)
    print(f"{name:44s} {c*1e6:10.1f} us/iter   (T{K1}={ts[K1]*1e3:.1f}ms T{K2}={ts[K2]*1e3:.1f}ms)", flush=True)
    return c


def chain(body, K):
    @jax.jit
    def run(state, *rest):
        def f(i, st):
            return body(st, i, *rest)

        return jax.lax.fori_loop(0, K, f, state)

    return run


def main():
    cols = [
        jnp.asarray(rng.randint(0, 1 << 20, size=C, dtype=np.int32))
        for _ in range(11)
    ]
    idx = jnp.asarray(idx_np)

    def rmw_cols(st, i, ix):
        gs = [c[ix] for c in st]
        return [
            c.at[ix].set(g + 1, mode="drop", unique_indices=True)
            for c, g in zip(st, gs)
        ]

    bench("rmw 11 cols gather+scatter", lambda K: chain(rmw_cols, K), cols, idx)

    def ew(st, i, ix):
        return [c + jnp.int32(i) for c in st]

    bench("elementwise 11 cols full table", lambda K: chain(ew, K), cols, idx)

    a64 = jnp.asarray(rng.randint(1, 1 << 40, size=B).astype(np.int64))
    b64 = jnp.asarray(rng.randint(1, 1 << 20, size=B).astype(np.int64))

    bench("i64 div batch", lambda K: chain(lambda x, i, y: x // (y + i), K), a64, b64)
    bench("i64 mul batch", lambda K: chain(lambda x, i, y: x * (y + i), K), a64, b64)

    from gubernator_tpu.ops import buckets

    state = buckets.init_state(C)
    slot = np.arange(B, dtype=np.int32)
    b32 = buckets.make_batch32(
        slot,
        np.ones(B, dtype=bool),
        (slot % 2).astype(np.int32),
        np.zeros(B, np.int32),
        np.ones(B, np.int32),
        np.full(B, 1 << 30, np.int32),
        np.full(B, 3_600_000, np.int32),
    )
    rid = jnp.zeros(B, jnp.int32)
    now0 = jnp.int64(1_700_000_000_000)
    create = b32._replace(exists=jnp.zeros(B, bool))
    state, _ = buckets.apply_rounds32_jit(state, create, rid, jnp.int32(1), now0)

    def kern_chain(K):
        @jax.jit
        def run(st, req, rid):
            def f(i, c):
                st, _ = c
                st, packed = buckets.apply_rounds32(
                    st, req, rid, jnp.int32(1), now0 + i.astype(jnp.int64)
                )
                return jax.lax.optimization_barrier((st, packed))

            B = req.slot.shape[0]
            return jax.lax.fori_loop(0, K, f, (st, jnp.zeros((4, B), jnp.int32)))

        return run

    bench("apply_rounds32 (1 round)", kern_chain, state, b32, rid)

    # apply_batch without the rounds wrapper
    req64 = buckets.make_batch(
        slot,
        np.ones(B, dtype=bool),
        (slot % 2).astype(np.int32),
        np.zeros(B, np.int32),
        np.ones(B, np.int64),
        np.full(B, 1 << 30, np.int64),
        np.full(B, 3_600_000, np.int64),
    )

    def ab_chain(K):
        @jax.jit
        def run(st, req):
            def f(i, c):
                st, _ = c
                st, out = buckets.apply_batch(st, req, now0 + i.astype(jnp.int64))
                return jax.lax.optimization_barrier((st, out.status))

            return jax.lax.fori_loop(0, K, f, (st, jnp.zeros_like(req.hits, jnp.int32)))

        return run

    bench("apply_batch bare", ab_chain, state, req64)


if __name__ == "__main__":
    main()
