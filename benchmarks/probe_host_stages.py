"""Round-4 probe #2: where does the service-ingress host time go?

Runs the full V1Service columnar ingress (get_rate_limits_columns) on
the CPU backend (tunnel-free) and prices each stage:

  parse     (native JSON -> columns; only in the HTTP twin)
  route     validation + hash keys + ownership
  plan      shard-bucket + C++ round planning
  pack      padded array fill + wire pack
  dispatch  device_put + jit call enqueue
  readback  the blocking device->host transfer
  decode    narrow decode + slot-table commit
  render    result scatter (+ JSON render in the HTTP twin)

Usage: python benchmarks/probe_host_stages.py [n_threads]
"""

import cProfile
import io
import os
import pstats
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache_cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np

from gubernator_tpu.service import IngressColumns, ServiceConfig, V1Service
from gubernator_tpu.types import PeerInfo

N_KEYS = 100_000
BATCH = 1000
ITERS = 30


def svc_cols(tid, i):
    ids = (np.arange(BATCH) * 2654435761 + tid * 97 + i) % N_KEYS
    return IngressColumns(
        names=["bench"] * BATCH,
        unique_keys=[f"s{tid}:{k}" for k in ids],
        algorithm=(ids % 2).astype(np.int32),
        behavior=np.zeros(BATCH, np.int32),
        hits=np.ones(BATCH, np.int64),
        limit=np.full(BATCH, 1_000_000, np.int64),
        duration=np.full(BATCH, 3_600_000, np.int64),
    )


def main():
    n_threads = int(sys.argv[1]) if len(sys.argv) > 1 else 1
    svc = V1Service(ServiceConfig(cache_size=131_072))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:1", is_owner=True)])
    # Warm every pad bucket + jit
    for i in range(3):
        svc.get_rate_limits_columns(svc_cols(0, 1000 + i))

    # Throughput without profiler
    def worker(tid, iters):
        for i in range(iters):
            svc.get_rate_limits_columns(svc_cols(tid, i))

    def epoch():
        t0 = time.perf_counter()
        if n_threads == 1:
            worker(0, ITERS)
        else:
            ts = [
                threading.Thread(target=worker, args=(t, ITERS))
                for t in range(n_threads)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        return time.perf_counter() - t0

    epoch()  # warm coalesced pad buckets (multi-thread merges hit new shapes)
    dt = min(epoch() for _ in range(2))
    cps = BATCH * ITERS * n_threads / dt
    print(f"threads={n_threads} ingress={cps:,.0f} checks/s "
          f"({dt/ITERS/n_threads*1e3:.2f} ms/batch serial-equiv)")

    # Profile single-threaded
    pr = cProfile.Profile()
    pr.enable()
    worker(1, ITERS)
    pr.disable()
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(35)
    print(s.getvalue())
    svc.close()


if __name__ == "__main__":
    main()
