"""Round-3 probe #3: which arithmetic op burns the 30ms?

Times individual vector ops over B=131072 lanes, chained in one jit.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_enable_x64", True)

B = 131_072
ITERS = 16

rng = np.random.RandomState(7)
a64 = jnp.asarray(rng.randint(1, 1 << 40, size=B).astype(np.int64))
b64 = jnp.asarray(rng.randint(1, 1 << 20, size=B).astype(np.int64))
a32 = jnp.asarray(rng.randint(1, 1 << 30, size=B, dtype=np.int32))
b32 = jnp.asarray(rng.randint(1, 1 << 15, size=B, dtype=np.int32))


def bench(name, fn, *args):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / ITERS
    del out
    print(f"{name:36s} {dt*1e6:10.1f} us/iter", flush=True)


def chain(body):
    @jax.jit
    def run(x, y):
        def f(i, x):
            return body(x, y)

        return jax.lax.fori_loop(0, ITERS, f, x)

    return run


def main():
    bench("i64 add", chain(lambda x, y: x + y), a64, b64)
    bench("i64 mul", chain(lambda x, y: x * y), a64, b64)
    bench("i64 div", chain(lambda x, y: x // y), a64, b64)
    bench("i64 mod", chain(lambda x, y: x % y), a64, b64)
    bench("i64 divmod pow2", chain(lambda x, y: x // (1 << 20)), a64, b64)
    bench("i32 add", chain(lambda x, y: x + y), a32, b32)
    bench("i32 mul", chain(lambda x, y: x * y), a32, b32)
    bench("i32 div", chain(lambda x, y: x // y), a32, b32)
    bench("i32 mod", chain(lambda x, y: x % y), a32, b32)
    bench("i64 where", chain(lambda x, y: jnp.where(x > y, x, y)), a64, b64)
    bench("i64 cmp+sel x5", chain(
        lambda x, y: jnp.where(x > y, x, jnp.where(x < y, y, jnp.where(x == y, x + 1, jnp.where(x > 0, y + 1, jnp.where(y > 0, x - 1, y)))))
    ), a64, b64)
    bench("f32 div", chain(lambda x, y: x / y),
          a32.astype(jnp.float32), b32.astype(jnp.float32))

    from gubernator_tpu.ops.buckets import _muldiv128, _leak_amounts

    bench("muldiv128", chain(lambda x, y: _muldiv128(x, y, y + 3)[0]), a64, b64)
    bench("leak_amounts", chain(lambda x, y: _leak_amounts(jnp.minimum(x, y), x, y)[0]), a64, b64)


if __name__ == "__main__":
    main()
