"""Round-4 probe #3: WHICH narrowing piece costs the 5.2ms?

probe_r4_bisect found apply_rounds32 (narrow wire) at 5770us/batch vs
apply_rounds (wide) at 515us — the narrowing layer dominates the
production kernel ~11x.  This probe prices the layer's pieces by
building apply_rounds32 variants with parts disabled:

  A   full apply_rounds32                      (baseline)
  A1  no -2 sentinel: skip the pre-batch row gather + pre_exp compare
      (delta clips instead of passing through)
  A2  narrow INPUT only: upcast i32 inputs, return the wide i64 packed
      output untouched (isolates the input upcast cost)
  A3  output delta+cast WITHOUT the stack reorder: subtract/clip rows
      in-place on the i64[4,B] then astype (isolates jnp.stack)
  B   wide apply_rounds                        (floor, re-measured)

Each measured by the same differential chained-K method.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from gubernator_tpu.ops import buckets

B = 131_072
C = 262_144
K_LO, K_HI = 4, 20
NOW = 1_700_000_000_000

rng = np.random.RandomState(7)
_ = np.asarray(jnp.zeros((1,), jnp.int32))  # honest mode

_I64 = jnp.int64
_I32 = jnp.int32


def measure(name, make_fn, state, *args):
    ts = {}
    for K in (K_LO, K_HI):
        fn = make_fn(K)
        st, out = fn(state, *args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        best = float("inf")
        for _ in range(4):
            t0 = time.perf_counter()
            st, out = fn(st, *args)
            np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        ts[K] = best
        del st, out
    us = (ts[K_HI] - ts[K_LO]) / (K_HI - K_LO) * 1e6
    print(f"{name:58s} {us:9.1f} us/batch", flush=True)
    return us


def chain(body):
    def make(K):
        @jax.jit
        def run(state, *args):
            def f(i, c):
                st, _ = c
                st, out = body(st, i, *args)
                return jax.lax.optimization_barrier((st, out))

            st0, out0 = body(state, jnp.asarray(0, jnp.int32), *args)
            return jax.lax.fori_loop(1, K, f, (st0, out0))

        return run

    return make


def upcast(req32, now):
    return buckets.RequestBatch(
        slot=req32.slot, exists=req32.exists, algorithm=req32.algorithm,
        behavior=req32.behavior, hits=req32.hits.astype(_I64),
        limit=req32.limit.astype(_I64), duration=req32.duration.astype(_I64),
        greg_expire=now + req32.greg_expire_delta.astype(_I64),
        greg_duration=req32.greg_duration.astype(_I64),
        occ=req32.occ, write=req32.write,
    )


def main():
    one = jnp.asarray(1, jnp.int32)
    slot = rng.permutation(C)[:B].astype(np.int32)
    n = B
    b32 = jax.device_put(buckets.make_batch32(
        slot, np.ones(n, bool), (slot % 2).astype(np.int32),
        np.zeros(n, np.int32), np.ones(n, np.int32),
        np.full(n, 1 << 30, np.int32), np.full(n, 3_600_000, np.int32),
    ))
    b64 = jax.device_put(buckets.make_batch(
        slot, np.ones(n, bool), (slot % 2).astype(np.int32),
        np.zeros(n, np.int32), np.ones(n, np.int64),
        np.full(n, 1 << 30, np.int64), np.full(n, 3_600_000, np.int64),
    ))
    rid = jax.device_put(np.zeros(n, np.int32))

    state = buckets.init_state(C)
    create = jax.device_put(
        buckets.make_batch(
            slot, np.zeros(n, bool), (slot % 2).astype(np.int32),
            np.zeros(n, np.int32), np.ones(n, np.int64),
            np.full(n, 1 << 30, np.int64), np.full(n, 3_600_000, np.int64),
        )
    )
    state, _p = buckets.apply_rounds_jit(state, create, rid, one, NOW)
    np.asarray(_p[:1, :1])

    now_dev = jnp.asarray(NOW, _I64)

    def a_body(st, i, b, r):
        return buckets.apply_rounds32(st, b, r, one, now_dev + i.astype(_I64))

    measure("A  apply_rounds32 full", chain(a_body), state, b32, rid)

    # A1: no -2 sentinel (no pre-batch gather; deltas clip)
    def a1_body(st, i, b, r):
        now = now_dev + i.astype(_I64)
        req = upcast(b, now)
        st, packed64 = buckets.apply_rounds(st, req, r, one, now)
        hi = jnp.asarray((1 << 31) - 1, _I64)

        def delta(v):
            d = v - now
            return jnp.where(v == 0, -1, jnp.clip(d, 0, hi))

        packed32 = jnp.stack(
            (packed64[0], jnp.clip(packed64[1], 0, hi),
             delta(packed64[2]), delta(packed64[3]))
        ).astype(_I32)
        return st, packed32

    measure("A1 no sentinel pre-gather", chain(a1_body), state, b32, rid)

    # A2: narrow input only, wide output
    def a2_body(st, i, b, r):
        now = now_dev + i.astype(_I64)
        return buckets.apply_rounds(st, upcast(b, now), r, one, now)

    measure("A2 narrow input, wide output", chain(a2_body), state, b32, rid)

    # A3: delta on rows without restacking (subtract a row-constant
    # offset vector, then one astype)
    def a3_body(st, i, b, r):
        now = now_dev + i.astype(_I64)
        req = upcast(b, now)
        st, packed64 = buckets.apply_rounds(st, req, r, one, now)
        off = jnp.stack(
            (jnp.zeros((), _I64), jnp.zeros((), _I64), now, now)
        )[:, None]
        return st, (packed64 - off).astype(_I32)

    measure("A3 row-offset subtract + cast", chain(a3_body), state, b32, rid)

    def b_body(st, i, b, r):
        return buckets.apply_rounds(st, b, r, one, now_dev + i.astype(_I64))

    measure("B  apply_rounds wide (floor)", chain(b_body), state, b64, rid)

    # B2: wide kernel + plain i32 cast of all four rows (no deltas)
    def b2_body(st, i, b, r):
        st, packed64 = buckets.apply_rounds(st, b, r, one, now_dev + i.astype(_I64))
        return st, packed64.astype(_I32)

    measure("B2 wide + bare i32 cast", chain(b2_body), state, b64, rid)


if __name__ == "__main__":
    main()
