"""Round-4 probe #1: bisect the apply_rounds32 cost stack.

Round 3 left ~2-3ms of a 5-8ms 131k batch unattributed ("narrowing
wrapper overhead").  This probe prices each layer of the kernel stack
with the differential chained-K method (K batches inside ONE jit via
fori_loop + optimization_barrier, two K values, divide the difference —
tunnel RTT and fixed dispatch costs cancel):

  A  apply_rounds32 (narrow wire, the production kernel)    full stack
  B  apply_rounds   (wide 64-bit wire)                      A - B = narrowing
  C  apply_batch    (single application, no while_loop)     B - C = rounds loop
  D  apply_batch, scatter skipped (state passthrough)       C - D = hot scatter
  E  pre-gather + delta packing alone (the narrow pieces)   direct price
  F  rmw row scatter alone                                  scatter floor
  G  apply_batch, leaky block fed constants (no division)   C - G = leak divs

Each at capacity 262k and 2M (the cfg2 / cfg3 scales).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import gubernator_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from gubernator_tpu.ops import buckets

B = 131_072
K_LO, K_HI = 4, 20
NOW = 1_700_000_000_000

rng = np.random.RandomState(7)
_ = np.asarray(jnp.zeros((1,), jnp.int32))  # honest mode


def measure(name, make_fn, state, *args):
    """Differential chained-K timing of fn(state, *args) -> (state, out)."""
    ts = {}
    for K in (K_LO, K_HI):
        fn = make_fn(K)
        st, out = fn(state, *args)
        np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        best = float("inf")
        for _ in range(4):
            t0 = time.perf_counter()
            st, out = fn(st, *args)
            np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
            best = min(best, time.perf_counter() - t0)
        ts[K] = best
        del st, out
    us = (ts[K_HI] - ts[K_LO]) / (K_HI - K_LO) * 1e6
    print(f"{name:58s} {us:9.1f} us/batch", flush=True)
    return us


def chain(body):
    """K-batch chain: body(state, i) -> (state, out)."""

    def make(K):
        @jax.jit
        def run(state, *args):
            def f(i, c):
                st, _ = c
                st, out = body(st, i, *args)
                return jax.lax.optimization_barrier((st, out))

            st0, out0 = body(state, jnp.asarray(0, jnp.int32), *args)
            return jax.lax.fori_loop(1, K, f, (st0, out0))

        return run

    return make


def mk_batch64(slot):
    n = len(slot)
    return buckets.make_batch(
        slot,
        np.ones(n, bool),
        (slot % 2).astype(np.int32),
        np.zeros(n, np.int32),
        np.ones(n, np.int64),
        np.full(n, 1 << 30, np.int64),
        np.full(n, 3_600_000, np.int64),
    )


def mk_batch32(slot):
    n = len(slot)
    return buckets.make_batch32(
        slot,
        np.ones(n, bool),
        (slot % 2).astype(np.int32),
        np.zeros(n, np.int32),
        np.ones(n, np.int32),
        np.full(n, 1 << 30, np.int32),
        np.full(n, 3_600_000, np.int32),
    )


def apply_batch_noscatter(state, req, now):
    """apply_batch with the state commit cut out: same gathers + compute
    + output packing, state rides through untouched."""
    st, out = buckets.apply_batch(state, req, now, cold_cond=True)
    del st
    return state, buckets._pack_output(out)


def main():
    one = jnp.asarray(1, jnp.int32)

    caps = [int(a) for a in sys.argv[1:]] or [262_144, 2_097_152]
    for C in caps:
        print(f"--- capacity {C} ---", flush=True)
        slot = rng.permutation(C)[:B].astype(np.int32)
        b64 = jax.device_put(mk_batch64(slot))
        b32 = jax.device_put(mk_batch32(slot))
        rid = jax.device_put(np.zeros(B, np.int32))

        # Seed state: create all buckets once.
        state = buckets.init_state(C)
        create = jax.device_put(mk_batch64(slot)._replace(exists=jnp.zeros(B, bool)))
        state, _p = buckets.apply_rounds_jit(state, create, rid, one, NOW)
        np.asarray(_p[:1, :1])

        now_dev = jnp.asarray(NOW, jnp.int64)

        # A: production narrow kernel
        def a_body(st, i, b, r):
            return buckets.apply_rounds32(st, b, r, one, now_dev + i.astype(jnp.int64))

        measure("A apply_rounds32 (narrow, rounds loop)", chain(a_body), state, b32, rid)

        # B: wide kernel with rounds loop
        def b_body(st, i, b, r):
            return buckets.apply_rounds(st, b, r, one, now_dev + i.astype(jnp.int64))

        measure("B apply_rounds (wide, rounds loop)", chain(b_body), state, b64, rid)

        # C: single apply_batch, no while_loop
        def c_body(st, i, b):
            st, out = buckets.apply_batch(st, b, now_dev + i.astype(jnp.int64))
            return st, buckets._pack_output(out)

        measure("C apply_batch (wide, single, packed out)", chain(c_body), state, b64)

        # D: apply_batch minus the scatter (compute only)
        def d_body(st, i, b):
            return apply_batch_noscatter(st, b, now_dev + i.astype(jnp.int64))

        measure("D apply_batch compute only (no scatter)", chain(d_body), state, b64)

        # E: the narrowing pieces alone: pre-gather + delta/select pack
        def e_body(st, i, b):
            si = jnp.clip(b.slot, 0, C - 1)
            pre = st.hot[si]
            pre_exp = buckets._compose64(pre[:, 5], pre[:, 6])
            v = pre_exp + i.astype(jnp.int64)
            now = now_dev + i.astype(jnp.int64)
            hi = jnp.asarray((1 << 31) - 1, jnp.int64)
            d = v - now
            fits = (d >= 0) & (d <= hi)
            out = jnp.where(
                v == 0, -1,
                jnp.where(fits, d, jnp.where(v == pre_exp, -2, jnp.clip(d, 0, hi))),
            )
            packed = jnp.stack((out, out, out, out)).astype(jnp.int32)
            return st, packed

        measure("E pre-gather + delta pack alone", chain(e_body), state, b32)

        # F: row-scatter floor (gather rows, +1, scatter)
        def f_body(st, i, ix):
            g = st.hot[ix]
            return st._replace(
                hot=st.hot.at[ix].set(g + 1, mode="drop", unique_indices=True)
            ), g[:1]

        measure("F rmw hot-row scatter alone", chain(f_body), state, jnp.asarray(slot))

        # G: apply_batch with the leaky divisions replaced by constants
        orig = buckets._leak_amounts
        try:
            buckets._leak_amounts = lambda el, lim, rn: (
                jnp.zeros_like(el), jnp.zeros_like(el)
            )

            def g_body(st, i, b):
                st, out = buckets.apply_batch(st, b, now_dev + i.astype(jnp.int64))
                return st, buckets._pack_output(out)

            measure("G apply_batch, leak divisions stubbed", chain(g_body), state, b64)
        finally:
            buckets._leak_amounts = orig

        # H: apply_batch with occ_rem divisions active but reset selects
        # (sanity: G vs C isolates _leak_amounts only; the remaining divs
        # are rate_num//lim, dur_eff//lim, //hs, rem//SCALE shifts)
        del state, b64, b32, create


if __name__ == "__main__":
    main()
