"""Package build for gubernator_tpu.

The C++ host runtime (native/host_runtime.cpp) is self-building: the
package compiles it with g++ on first import and falls back to the pure
Python twins when no compiler is present, so no build_ext step is needed
here — the .cpp ships as package data.
"""

from setuptools import find_packages, setup

setup(
    name="gubernator-tpu",
    version="0.1.0",
    description=(
        "TPU-native distributed rate limiting: vectorized token/leaky "
        "buckets over sharded device state with Gubernator-compatible APIs"
    ),
    long_description=open("README.md").read(),
    long_description_content_type="text/markdown",
    packages=find_packages(include=["gubernator_tpu", "gubernator_tpu.*"]),
    package_data={
        "gubernator_tpu.native": ["host_runtime.cpp"],
        "gubernator_tpu.proto": ["*.proto"],
    },
    python_requires=">=3.10",
    install_requires=[
        "jax>=0.4.30",
        "numpy>=1.26",
        "grpcio>=1.60",
        "protobuf>=4.21",
    ],
    extras_require={
        # kubeconfig-based (out-of-cluster) k8s discovery
        "k8s": ["PyYAML>=6.0"],
    },
    entry_points={
        "console_scripts": [
            "gubernator-tpu=gubernator_tpu.cmd.server:main",
            "gubernator-tpu-cli=gubernator_tpu.cmd.cli:main",
            "gubernator-tpu-cluster=gubernator_tpu.cmd.cluster_main:main",
        ]
    },
)
