"""Benchmark: end-to-end rate-limit check throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference reports > 2,000 requests/s on a single
production node with batching (README.md:96-100; BASELINE.md).  The
headline here is the columnar bulk-ingress path (ShardStore.
apply_columns: C++ key resolution + round planning -> one vectorized
kernel dispatch per round), measured steady-state over a Zipf-ish key
mix (hot keys + long tail, mirroring BASELINE.json config 2).  The
dataclass path (`apply`, what the HTTP daemon uses per request today)
is measured too and reported inside the extra fields.

`--gate` evaluates the stable rows — the device kernels (differential
in-jit chaining so RTT cancels), the dispatch_overlap_ratio (how much
of the dispatch path's fixed cost the overlapped pipeline hides behind
device compute, a same-run ratio so device weather cancels), and the
service/peer throughput floors — against
benchmarks/gate_thresholds.json, with NOISE-ADJUSTED verdicts
(gate_verdict) so timer noise yields "inconclusive", never a flipped
verdict.  Exit 1 on regression; wired into `make bench` /
`make bench-gate`.
"""

import contextlib
import json
import sys
import time

import numpy as np

# Shared ceil-rank (nearest-rank) percentile: ALL p50/p99 sites below
# index the same way (the old `min(len-1, int(len*q))` floor-indexed,
# judging thin tails against the wrong sample — round-6 satellite fix;
# the shared implementation lives beside the /debug/latency snapshots).
from gubernator_tpu.saturation import percentile


def _jax_setup():
    import jax

    # Persistent compile cache: the TPU tunnel's remote compiles are
    # minutes each; cache them across processes/rounds.
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return jax


def measure_device(jax, now, samples: int = 5):
    """Tunnel-independent device rows (the stable numbers).

    Pre-stages a device-resident RequestBatch32, then measures chip cost
    per batch by DIFFERENTIAL in-jit chaining: run K batches inside ONE
    dispatch (fori_loop chaining donated state) for two different K and
    divide the time difference — the tunnel RTT and every fixed
    per-dispatch cost cancel exactly, leaving pure chip time.  (Round-3
    finding: a per-dispatch loop pays a multi-ms tunnel enqueue per
    batch, which would under-report the chip by >3x.)

    MEASUREMENT GOTCHA (tunnel): before the first device->host readback
    in a process, block_until_ready returns without waiting for
    execution (optimistic async mode) — timings taken then are enqueue
    costs, ~2000x too fast.  Any readback (even one scalar) switches
    the process into honest mode, so every timed region below ends in a
    small real readback.

    The `packed` output rides the loop carry behind an
    optimization_barrier: without it XLA dead-code-eliminates the whole
    output-packing computation from the timed kernel.
    """
    import jax.numpy as jnp

    from gubernator_tpu.ops import buckets

    dev_capacity = 262_144
    dev_batch = 131_072
    state = buckets.init_state(dev_capacity)
    slot = np.arange(dev_batch, dtype=np.int32)
    mk32 = lambda exists: jax.device_put(  # noqa: E731
        buckets.make_batch32(
            slot,
            np.full(dev_batch, exists, dtype=bool),
            (slot % 2).astype(np.int32),
            np.zeros(dev_batch, np.int32),
            np.ones(dev_batch, np.int32),
            np.full(dev_batch, 1 << 30, np.int32),
            np.full(dev_batch, 3_600_000, np.int32),
        )
    )
    rid = jax.device_put(np.zeros(dev_batch, np.int32))
    now_dev = jax.device_put(np.int64(now))
    one_round = jax.device_put(np.int32(1))

    def sync(arr):
        # A real (1-element) readback: the only reliable completion
        # barrier on the tunnel (see gotcha above).
        return np.asarray(arr[0, :1])

    create_b = mk32(False)
    steady_b = mk32(True)
    state, packed = buckets.apply_rounds32_jit(state, create_b, rid, one_round, now_dev)
    sync(packed)  # warmup: compile + create all buckets + honest mode

    def _chain(K):
        @jax.jit
        def run(st, req, rid_a):
            B = req.slot.shape[0]

            def f(i, c):
                st, _ = c
                st, packed = buckets.apply_rounds32(
                    st, req, rid_a, one_round, now_dev + i.astype(jnp.int64)
                )
                return jax.lax.optimization_barrier((st, packed))

            st, packed = jax.lax.fori_loop(
                0, K, f, (st, jnp.zeros((4, B), jnp.int32))
            )
            return st, packed

        return run

    # dK=64: at dK=16 the tunnel-weather error bar is ~±1.5ms/batch
    # (round-4 probe finding — it had produced impossible orderings).
    k_lo, k_hi = 4, 68
    chain_t = {}
    for K in (k_lo, k_hi):
        fn = _chain(K)
        st2, pk = fn(state, steady_b, rid)
        sync(pk)  # compile + drain
        best = float("inf")
        for _ in range(samples):
            t0 = time.perf_counter()
            st2, pk = fn(st2, steady_b, rid)
            sync(pk)
            best = min(best, time.perf_counter() - t0)
        chain_t[K] = best
    device_batch_us = (chain_t[k_hi] - chain_t[k_lo]) / (k_hi - k_lo) * 1e6
    device_cps = dev_batch / (device_batch_us / 1e6)

    # Per-dispatch number (includes the tunnel's per-call enqueue cost;
    # reported separately for continuity with earlier rounds).
    k_iters, dispatch_batch_us = 16, float("inf")
    for _ in range(2):
        state, packed = buckets.apply_rounds32_jit(state, steady_b, rid, one_round, now_dev)
        sync(packed)  # drain queue before timing
        t0 = time.perf_counter()
        for _ in range(k_iters):
            state, packed = buckets.apply_rounds32_jit(
                state, steady_b, rid, one_round, now_dev
            )
        sync(packed)
        dt = time.perf_counter() - t0
        dispatch_batch_us = min(dispatch_batch_us, dt / k_iters * 1e6)

    # Service-sized batches: measured device cost per batch at 256 /
    # 1024 / 4096 lanes (the reference's "<1 ms most responses" bar is
    # judged at its 1000-item request cap).  Same differential chain
    # method; the spread across samples of the K=520 chain bounds the
    # on-chip variance (no tunnel in these numbers).
    small_batch_us = {}
    for sb in (256, 1024, 4096):
        sslot = np.arange(sb, dtype=np.int32)
        sbatch = jax.device_put(
            buckets.make_batch32(
                sslot,
                np.ones(sb, dtype=bool),
                (sslot % 2).astype(np.int32),
                np.zeros(sb, np.int32),
                np.ones(sb, np.int32),
                np.full(sb, 1 << 30, np.int32),
                np.full(sb, 3_600_000, np.int32),
            )
        )
        srid = jax.device_put(np.zeros(sb, np.int32))
        sstate = buckets.init_state(65_536)
        screate = jax.device_put(sbatch._replace(exists=np.zeros(sb, bool)))
        sstate, spacked = buckets.apply_rounds32_jit(
            sstate, screate, srid, one_round, now_dev
        )
        sync(spacked)
        # Small batches cost ~tens of us on chip, far below the tunnel's
        # ms-scale jitter — so the K spread must be large enough that
        # the differential signal (dK * per-batch cost) clears the
        # noise: dK=512 puts a 50 us/batch kernel at ~25 ms of signal.
        # Round-4 shipped device_us_b256 = -33 us: tunnel weather can
        # still underflow the differential.  Sample in rounds until the
        # noise estimate (gap between the two fastest runs of each
        # chain, in per-batch units) is < 20% of the point estimate,
        # clamp at 0, and mark below-floor rows explicitly.
        times = {}
        k_pair = (8, 520)
        fns = {}
        for K in k_pair:
            fns[K] = _chain(K)
            sstate, spk = fns[K](sstate, sbatch, srid)
            sync(spk)
            times[K] = []
        dk = k_pair[1] - k_pair[0]
        per_batch = worst = noise = 0.0
        for _round in range(6):
            for K in k_pair:
                for _ in range(max(samples - 1, 2)):
                    t0 = time.perf_counter()
                    sstate, spk = fns[K](sstate, sbatch, srid)
                    sync(spk)
                    times[K].append(time.perf_counter() - t0)
            lo_s = sorted(times[k_pair[0]])
            hi_s = sorted(times[k_pair[1]])
            per_batch = (hi_s[0] - lo_s[0]) / dk
            worst = (hi_s[-1] - lo_s[0]) / dk
            noise = ((hi_s[1] - hi_s[0]) + (lo_s[1] - lo_s[0])) / dk
            if per_batch > 0 and noise < 0.2 * per_batch:
                break
        below_floor = per_batch <= 0 or noise >= per_batch
        small_batch_us[sb] = (
            max(per_batch, 0.0) * 1e6,
            worst * 1e6,
            below_floor,
            noise * 1e6,
        )

    # Single-dispatch completion latency distribution (dispatch ->
    # forced completion, minimal transfer).  On this host each sample
    # includes one tunnel RTT; on a local chip this is the device p99.
    dlat = []
    for _ in range(40):
        t_b = time.perf_counter()
        state, packed = buckets.apply_rounds32_jit(
            state, steady_b, rid, one_round, now_dev
        )
        sync(packed)
        dlat.append((time.perf_counter() - t_b) * 1000.0)
    dlat.sort()
    return {
        "device_batch_us": device_batch_us,
        "device_cps": device_cps,
        "dispatch_batch_us": dispatch_batch_us,
        "small_batch_us": small_batch_us,
        "dispatch_p50": percentile(dlat, 0.50),
        "dispatch_p99": percentile(dlat, 0.99),
        "dispatch_lat_n_samples": len(dlat),
    }


def measure_device_zipf(jax, now, samples: int = 5):
    """Device cost of the PRODUCTION-SHAPED Zipf batch at 2M total
    capacity (two-tier table: 262,144-slot front + 1,835,008-slot back
    resident in HBM).

    The synthetic rows in measure_device scatter all 131,072 lanes into
    unique slots; real Zipf traffic repeats keys, and the grouped
    planner (gt_batch_plan_grouped) collapses each uniform duplicate
    group to ONE scattering lane — so the production dispatch writes
    only ~unique-key rows.  This row measures exactly what
    apply_columns dispatches for the headline workload: the C++
    planner's actual plan (slots/rounds/occ/write) for the Zipf batch,
    chained K batches in-jit (same differential method).  The front
    table prices the scatter; the back tier holds the capacity (zero
    moves in steady state — the working set is front-resident, which
    is the design's whole point; churn costs ride the amortized move
    program, exercised by bench_full cfg3)."""
    import jax.numpy as jnp

    from gubernator_tpu import native
    from gubernator_tpu.models.shard import make_columns
    from gubernator_tpu.ops import buckets

    front_cap, back_cap = 262_144, 2_097_152 - 262_144
    batch = 131_072
    rng = np.random.RandomState(42)
    n_keys = 100_000
    hot = rng.randint(0, n_keys // 10, size=batch)
    cold = rng.randint(0, n_keys, size=batch)
    key_ids = np.where(rng.random(batch) < 0.8, hot, cold)
    keys = [f"bench_account:{k}" for k in key_ids]
    cols = make_columns(
        (key_ids % 2).astype(np.int32), np.zeros(batch, np.int32),
        np.ones(batch, np.int64), np.full(batch, 1 << 30, np.int64),
        np.full(batch, 3_600_000, np.int64), batch,
    )

    table = native.NativeSlotTable(front_cap)
    table.enable_back(back_cap)
    pl = native.NativeBatchPlanner(table, keys, now)
    from gubernator_tpu.types import Behavior

    rid, slots, exists, occ, write, n_rounds = pl.plan_grouped(
        cols, int(Behavior.RESET_REMAINING)
    )
    write_frac = float(write.mean())

    assert n_rounds == 1, n_rounds  # grouped Zipf plan is single-round
    state = buckets.init_state(front_cap)
    back = buckets.init_back(back_cap)  # resident: the capacity is real
    back = jax.device_put(back)
    mk = lambda ex: jax.device_put(  # noqa: E731
        buckets.make_batch32(
            slots, ex, cols.algo.astype(np.int32),
            np.zeros(batch, np.int32), np.ones(batch, np.int32),
            np.full(batch, 1 << 30, np.int32),
            np.full(batch, 3_600_000, np.int32),
            occ=occ, write=write,
        )
    )
    rid_dev = jax.device_put(rid)
    nr = jax.device_put(np.int32(n_rounds))
    now_dev = jax.device_put(np.int64(now))

    def sync(arr):
        return np.asarray(arr[0, :1])

    state, packed = buckets.apply_rounds32_jit(
        state, mk(exists), rid_dev, nr, now_dev
    )
    sync(packed)
    steady = mk(np.ones(batch, bool))

    def _chain(K):
        @jax.jit
        def run(st, req, rid_a):
            B = req.slot.shape[0]

            def f(i, c):
                st, _ = c
                st, packed = buckets.apply_rounds32(
                    st, req, rid_a, nr, now_dev + i.astype(jnp.int64)
                )
                return jax.lax.optimization_barrier((st, packed))

            st, packed = jax.lax.fori_loop(
                0, K, f, (st, jnp.zeros((4, B), jnp.int32))
            )
            return st, packed

        return run

    k_lo, k_hi = 4, 68  # dK=64: see measure_device's error-bar note
    chain_t = {}
    for K in (k_lo, k_hi):
        fn = _chain(K)
        st2, pk = fn(state, steady, rid_dev)
        sync(pk)
        best = float("inf")
        for _ in range(samples):
            t0 = time.perf_counter()
            st2, pk = fn(st2, steady, rid_dev)
            sync(pk)
            best = min(best, time.perf_counter() - t0)
        chain_t[K] = best
    del back
    us = (chain_t[k_hi] - chain_t[k_lo]) / (k_hi - k_lo) * 1e6
    return {
        "device_zipf_batch_us": us,
        "device_zipf_cps": batch / (us / 1e6),
        "zipf_write_fraction": write_frac,
        "zipf_n_rounds": int(n_rounds),
        "total_capacity": front_cap + back_cap,
    }


def measure_dispatch_pipeline(jax, now, samples: int = 5, fuse: int = 4):
    """dispatch_batch_us_incl_tunnel: per-batch cost of the dispatch
    path AS THE OVERLAPPED PIPELINE LAUNCHES IT — the single-buffer
    packed dict wire (what _stage_columns uploads), launched in fused
    groups of `fuse` when the gate is backlogged
    (ColumnarPipeline._launch_group), enqueued back-to-back with
    donated state and synced once.  The fixed per-dispatch cost (on a
    tunnel device, a full RPC enqueue per program) amortizes over the
    group, so this row approaches device_batch_us as the pipeline
    hides host dispatch overhead — which is exactly what
    dispatch_overlap_ratio = device_batch_us / THIS gates.

    (Through round 5 this row measured one 11-array RequestBatch32
    program per batch with no amortization: 9.5ms against 4.4ms of
    compute, i.e. the dispatch path cost 2.2x the chip time.  The
    pipeline exists to hide that; the row now measures the path it
    actually takes.)  Also returns the solo (unfused) per-dispatch
    cost for continuity."""
    from gubernator_tpu.models.shard import make_columns
    from gubernator_tpu.ops import buckets

    dev_capacity = 262_144
    dev_batch = 131_072
    state = buckets.init_state(dev_capacity)
    slot = np.arange(dev_batch, dtype=np.int32)
    cols = make_columns(
        (slot % 2).astype(np.int32), np.zeros(dev_batch, np.int32),
        np.ones(dev_batch, np.int64), np.full(dev_batch, 1 << 30, np.int64),
        np.full(dev_batch, 3_600_000, np.int64), dev_batch,
    )
    cfg_idx, table = buckets.build_config_dict(cols, now)

    def wire_for(exists):
        return buckets.pack_dict_wire(
            slot[None, :],
            np.full((1, dev_batch), exists, dtype=bool),
            np.ones((1, dev_batch), dtype=bool),
            cfg_idx[None, :].astype(np.uint8),
            np.zeros((1, dev_batch), np.int32),
            np.zeros((1, dev_batch), np.int32),
            table,
        )[0]

    def sync(arr):
        return np.asarray(arr[:1, :1] if arr.ndim == 2 else arr[:1, :1, :1])

    create_w = jax.device_put(wire_for(False))
    state, packed = buckets.apply_rounds_packed_jit(state, create_w, 1, now)
    sync(packed)  # warmup: compile + create buckets + honest mode

    steady = wire_for(True)
    # donate_wires=False: the measurement reuses the same uploaded
    # wires every call (production uploads fresh ones and donates).
    fn = buckets.fused_packed_jit(fuse, wide=False, donate_wires=False)
    wires = [jax.device_put(steady) for _ in range(fuse)]
    nr = np.ones(fuse, np.int32)
    nowv = np.full(fuse, now, np.int64)
    state, stacked = fn(state, *wires, nr, nowv)
    sync(stacked)  # compile + drain
    calls, fused_us = 6, float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, stacked = fn(state, *wires, nr, nowv)
        sync(stacked)
        dt = time.perf_counter() - t0
        fused_us = min(fused_us, dt / (calls * fuse) * 1e6)

    solo_w = jax.device_put(steady)
    state, packed = buckets.apply_rounds_packed_jit(state, solo_w, 1, now)
    sync(packed)
    solo_us = float("inf")
    for _ in range(samples):
        t0 = time.perf_counter()
        for _ in range(calls * fuse):
            state, packed = buckets.apply_rounds_packed_jit(
                state, solo_w, 1, now
            )
        sync(packed)
        solo_us = min(solo_us, (time.perf_counter() - t0) / (calls * fuse) * 1e6)
    return {
        "dispatch_batch_us": fused_us,
        "dispatch_solo_batch_us": solo_us,
        "dispatch_fuse": fuse,
    }


def _ingress_harness(n_threads: int, svc_iters: int,
                     n_keys: int = 100_000):
    """Build ONE warmed V1Service ingress harness; returns
    (run_epoch, close) where run_epoch() drives n_threads concurrent
    workers of svc_iters 1000-item batches each through
    get_rate_limits_columns and returns (checks_per_sec, latencies).
    Shared by the headline ingress row (measure_service_ingress) and
    the plane-overhead rows (_overhead_pairs): the overhead rows
    toggle their plane BETWEEN epochs on the SAME warmed service, so
    every off/on comparison shares one weather window instead of
    paying a fresh multi-second service warmup whose jitter swamps a
    ~0% effect."""
    import threading

    from gubernator_tpu.service import IngressColumns, ServiceConfig, V1Service
    from gubernator_tpu.types import PeerInfo

    svc = V1Service(ServiceConfig(cache_size=131_072))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:1", is_owner=True)])
    svc_batch = 1000
    # Pad-ladder warmup: coalesced flush sizes land in pow2 pad buckets
    # that vary with thread timing; compile the whole reachable ladder
    # up front (what a production daemon's GUBER_WARMUP_SHAPES does) so
    # the measured epoch's steady_recompiles==0 gate judges shape
    # CHURN, not warmup coverage luck.
    svc.store.warmup(
        1_700_000_000_000,
        warm_shapes=[1000, 2000, 4000, 8000, 16000, 32000, 64000],
    )

    def svc_cols(tid, i):
        # RandomState is not thread-safe: derive ids deterministically.
        ids = (np.arange(svc_batch) * 2654435761 + tid * 97 + i) % n_keys
        return IngressColumns(
            names=["bench"] * svc_batch,
            unique_keys=[f"s{tid}:{k}" for k in ids],
            algorithm=(ids % 2).astype(np.int32),
            behavior=np.zeros(svc_batch, np.int32),
            hits=np.ones(svc_batch, np.int64),
            limit=np.full(svc_batch, 1_000_000, np.int64),
            duration=np.full(svc_batch, 3_600_000, np.int64),
        )

    svc.get_rate_limits_columns(svc_cols(0, 0))  # warm the 1024-pad shape

    def run_epoch():
        lats: list = []
        lock = threading.Lock()

        def worker(tid):
            mine = []
            for i in range(svc_iters):
                cols = svc_cols(tid, i)
                t_b = time.perf_counter()
                svc.get_rate_limits_columns(cols)
                mine.append(time.perf_counter() - t_b)
            with lock:
                lats.extend(mine)

        ts = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.perf_counter() - t0
        return svc_batch * svc_iters * n_threads / dt, lats

    def start_flow():
        """CONTINUOUS load: workers loop batches until stop, bumping
        per-thread check counters (one owner per slot — no lock; the
        reader sums a racy-but-monotone snapshot).  The overhead rows
        toggle their plane at interval boundaries of ONE uninterrupted
        flow: epoch-style runs restart the worker pool per leg, and
        the restart re-rolls the coalescing alignment (which 1000-lane
        sub-batches fuse into which launches), a throughput mode worth
        ±15% on the 2-core box — interval deltas of a steady flow only
        ever differ by what the toggle itself does.  Returns
        (read_checks, stop)."""
        stop = threading.Event()
        slots = [0] * n_threads

        def flow(tid):
            i = 0
            while not stop.is_set():
                svc.get_rate_limits_columns(svc_cols(tid, i))
                i += 1
                slots[tid] = i

        ts = [threading.Thread(target=flow, args=(t,), daemon=True)
              for t in range(n_threads)]
        for t in ts:
            t.start()

        def read_checks() -> int:
            return sum(slots) * svc_batch

        def stop_flow():
            stop.set()
            for t in ts:
                t.join()

        return read_checks, stop_flow

    return run_epoch, start_flow, svc.close


def measure_service_ingress(n_threads: int = 32, svc_iters: int = 10,
                            n_keys: int = 100_000):
    """The full V1Service request path (validation, ownership routing,
    metrics, 1000-item cap — gubernator.go:116-227) fed by
    get_rate_limits_columns: what the gateway/gRPC edges execute per
    multi-item request.  Batches are capped at 1000 (reference parity),
    so throughput comes from concurrent clients pipelining through the
    ColumnarPipeline locks; on the tunnel each batch pays one ~120ms
    readback, so 32 concurrent callers keep the pipeline deep enough
    that the host cost is the measured ceiling (the reference benches
    100-way, benchmark_test.go:117).  Shared by main() and the --gate
    fallback so the ingress threshold is evaluable standalone.
    Returns (checks_per_sec, p50_ms, p99_ms, n_samples,
    steady_recompiles) — the sample count rides along so gate verdicts
    can discount thin tails, and steady_recompiles is the XLA-telemetry
    count of backend compiles DURING the measured epoch (after the
    warmup ladder + warm epoch marked the plane steady): shape churn in
    steady state, gated at == 0 so a recompile silently taxing the
    headline row fails `make bench-gate` instead of reading as
    mysterious latency."""
    from gubernator_tpu import telemetry

    telemetry.begin_warmup()
    run_epoch, _start_flow, close = _ingress_harness(n_threads, svc_iters, n_keys)
    # Untimed warm epoch: coalesced flush sizes hit pad buckets whose
    # FIRST dispatch pays a multi-second executable load on a remote
    # device (a long-running daemon warms these at startup,
    # GUBER_WARMUP_SHAPES); measure steady state.
    run_epoch()
    telemetry.mark_steady()
    compiles_before = telemetry.compile_count()
    service_cps, svc_lat = run_epoch()
    # None, not 0, when compiles are unobservable (plane disabled or
    # the jax.monitoring listener failed to register): a 0 from a blind
    # counter would pass the ==0 gate vacuously — the caller must SKIP.
    steady_recompiles = (
        telemetry.compile_count() - compiles_before
        if telemetry.listener_active() else None
    )
    svc_lat.sort()
    svc_p50 = percentile(svc_lat, 0.50) * 1000.0
    svc_p99 = percentile(svc_lat, 0.99) * 1000.0
    close()
    return service_cps, svc_p50, svc_p99, len(svc_lat), steady_recompiles


def _overhead_pairs(set_off, set_on, n_threads: int, iters: int,
                    pairs: int, interval_s: float = 0.5):
    """Shared harness of the three plane-overhead gate rows: ONE
    warmed service under ONE continuous flow of ingress load, the
    plane toggled at interval boundaries, returning
    (ratio, best_off_cps, best_on_cps, noise).  Three defenses
    against host weather on the 2-core dev box (single-interval
    absolutes swing 3x when anything else breathes):

    - CONTINUOUS flow, not epochs: restarting the worker pool per leg
      re-rolls the coalescing alignment (which sub-batches fuse into
      which launches), a throughput mode worth ±15% that an off/on
      pair straddles at random.  Interval deltas of one steady flow
      share alignment, caches, and thermal state — the only thing
      that changes at a boundary is the knob.
    - ABBA quads: each sample is one off,on,on,off (alternating
      on,off,off,on) quad whose ratio (on1+on2)/(off1+off2) cancels
      linear drift EXACTLY within the quad — ramp (allocator growth,
      cache decay, page-in) cannot masquerade as overhead in either
      direction.
    - MEDIAN of quad ratios with a seeded-bootstrap SD as the row's
      noise: a weather gust lands on one quad, the median ignores it,
      and the gate's straddle verdict (gate_verdict) judges the
      estimator actually used — a still-straddling band reads SKIP
      (inconclusive), never a flipped verdict.

    `iters` sizes the pre-flow warm epoch (executable loads); `pairs`
    is the quad count."""
    from gubernator_tpu import telemetry

    import random as _random
    import statistics as _statistics

    telemetry.begin_warmup()
    run_epoch, start_flow, close = _ingress_harness(n_threads, iters)
    run_epoch()  # untimed warm epoch (first-dispatch executable loads)
    telemetry.mark_steady()
    read_checks, stop_flow = start_flow()
    try:
        time.sleep(4 * interval_s)  # flow reaches steady coalescing
        ratios, offs, ons = [], [], []
        pairs = max(int(pairs), 2)
        rng = _random.Random(0xC057)
        while True:
            if len(ratios) % 2:
                quad = [True, False, False, True]
            else:
                quad = [False, True, True, False]
            q_off, q_on = 0.0, 0.0
            for flag in quad:
                (set_on if flag else set_off)()
                c0 = read_checks()
                t0 = time.perf_counter()
                time.sleep(interval_s)
                dt = time.perf_counter() - t0
                rate = (read_checks() - c0) / dt
                if flag:
                    q_on += rate
                    ons.append(rate)
                else:
                    q_off += rate
                    offs.append(rate)
            ratios.append(q_on / max(q_off, 1.0))
            if len(ratios) < pairs:
                continue
            ratio = _statistics.median(ratios)
            boot = [
                _statistics.median(rng.choices(ratios, k=len(ratios)))
                for _ in range(256)
            ]
            noise = min(_statistics.pstdev(boot), 0.2 * ratio)
            # ADAPTIVE PRECISION: keep adding quads until the noise
            # band can support a verdict (a ~1.0 truth needs ~±0.015
            # to clear a 0.95 floor), capped at 3x the requested
            # quads — ambient host contention comes in minutes-long
            # regimes, and when one is in force no finite run gets a
            # tight band: the cap ends in an honest SKIP instead of
            # burning the whole gate budget.
            if noise <= 0.015 or len(ratios) >= 3 * pairs:
                return ratio, max(offs), max(ons), noise
    finally:
        stop_flow()
        close()


def measure_tracing_overhead(n_threads: int = 8, iters: int = 8,
                             pairs: int = 10):
    """Same-run tracing overhead: headline ingress checks/s with
    GUBER_TRACE_SAMPLE=0 (the shipped default — every hook is one
    comparison returning the no-op singleton) over the same path with
    tracing force-disabled ('compiled out': tracing.force_disable, the
    as-if-the-module-did-not-exist baseline).  All legs run
    back-to-back in THIS process (ABBA interval quads toggled on one
    continuously loaded warmed service, median quad ratio —
    _overhead_pairs) so device/host weather cancels; the gate floors
    the ratio at 0.95 — the guards must cost <5% even on a noisy host,
    and ~0% in truth.  Returns (ratio, off_cps, s0_cps, noise)."""
    from gubernator_tpu import tracing

    prev_rate = tracing.sample_rate()
    try:
        return _overhead_pairs(
            lambda: tracing.force_disable(True),
            lambda: (tracing.force_disable(False),
                     tracing.set_sample_rate(0.0)),
            n_threads, iters, pairs,
        )
    finally:
        # One restore covering every leg: an off-leg failure must not
        # leave the process force-disabled contrary to its environment.
        tracing.force_disable(False)
        tracing.set_sample_rate(prev_rate)


def measure_xla_telemetry_overhead(n_threads: int = 8, iters: int = 8,
                                   pairs: int = 10):
    """Same-run XLA-telemetry overhead (the PR 4 playbook applied to
    telemetry.py): headline ingress checks/s with GUBER_XLA_TELEMETRY
    on (the shipped default — the launch hook is one branch plus a
    per-BATCH label scope) over the same path with the plane disabled,
    interleaved in THIS process so host weather cancels.  Gated at
    floor 0.95.  Returns (ratio, off_cps, on_cps, noise)."""
    from gubernator_tpu import telemetry

    prev = telemetry.enabled()
    try:
        return _overhead_pairs(
            lambda: telemetry.set_enabled(False),
            lambda: telemetry.set_enabled(True),
            n_threads, iters, pairs,
        )
    finally:
        telemetry.set_enabled(prev)


def measure_profiling_overhead(n_threads: int = 8, iters: int = 8,
                               pairs: int = 10):
    """Same-run cost-observatory overhead (the PR 4/PR 9 playbook
    applied to profiling.py): headline ingress checks/s with the plane
    ON (the shipped default — the 67 Hz sampler folding every thread's
    stack PLUS the per-batch tenant-ledger folds and the per-scope
    tags) over the same path with GUBER_PROFILE=0 (sampler tick = one
    branch, every scope hook one comparison; the tenant folds are
    always-on by design, so both legs pay them — the ratio isolates
    exactly what the knob controls).  ABBA interval quads on one
    continuously loaded warmed service, median quad ratio
    (_overhead_pairs).  Gated at floor 0.95.  Returns
    (ratio, off_cps, on_cps, noise)."""
    from gubernator_tpu import profiling

    prev = profiling.enabled()
    try:
        return _overhead_pairs(
            lambda: profiling.set_enabled(False),
            lambda: profiling.set_enabled(True),
            n_threads, iters, pairs,
        )
    finally:
        # One restore covering every leg (the telemetry-gate rule).
        profiling.set_enabled(prev)


def measure_blackbox_overhead(n_threads: int = 8, iters: int = 8,
                              pairs: int = 10):
    """Incident-black-box tap overhead (the PR 4/9/12 playbook applied
    to blackbox.py): headline ingress checks/s with the always-on wire
    tap recording every gateway frame into the byte-budgeted rings
    (the shipped default) over the same path force-disabled (every tap
    = one branch), ABBA interval quads on one continuously loaded
    warmed service, median quad ratio (_overhead_pairs).  Gated at
    floor 0.95.  Also counts audit-violation flight-recorder events
    seen during the run — the ratio only counts if conservation held
    at it.  Returns (ratio, off_cps, on_cps, noise, violations)."""
    from gubernator_tpu import blackbox, tracing

    def _violation_events() -> int:
        return sum(
            1 for e in tracing.events_snapshot(
                recorders=tracing.all_recorders()
            )
            if e.get("kind") == "audit-violation"
        )

    before = _violation_events()
    try:
        ratio, off_cps, on_cps, r_noise = _overhead_pairs(
            lambda: blackbox.force_disable(True),
            lambda: blackbox.force_disable(False),
            n_threads, iters, pairs,
        )
    finally:
        # One restore covering every leg (the telemetry-gate rule).
        blackbox.force_disable(False)
    return ratio, off_cps, on_cps, r_noise, _violation_events() - before


def measure_blackbox_bundle_write(budget_mb: int = 16):
    """Wall time of ONE incident bundle write at full rings (the
    freeze -> frame-log encode -> per-file fsync -> atomic rename
    path, blackbox.write_bundle): the cost a trigger pays off-thread
    while the hot path keeps running.  Rings are pre-filled to their
    byte budget with realistic 64-lane frames on every wire.  Returns
    (ms, ring_bytes)."""
    import shutil as _shutil
    import tempfile as _tempfile

    from gubernator_tpu import blackbox, wire

    d = _tempfile.mkdtemp(prefix="gubernator-bench-blackbox-")
    bb = blackbox.BlackBox(None, path=d, budget_mb=budget_mb)
    lanes = 64
    cols = (
        ["bench"] * lanes,
        [f"key-{i:06d}" for i in range(lanes)],
        [1] * lanes, [0] * lanes, [2] * lanes,
        [1000] * lanes, [60_000] * lanes,
    )
    try:
        for kind in (1, 3, 4, 5, 7):
            frame = wire.encode_columns_frame(cols, kind=kind)
            ring = bb.rings[blackbox._KIND_WIRE[kind]]
            per_rec = len(frame) + 32
            for _ in range(ring.budget // per_rec + 1):
                bb.tap("in", "10.0.0.9:1051", frame)
        ring_bytes = sum(bb.rings[w].stats()[1] for w in blackbox.WIRES)
        t0 = time.perf_counter()
        bb.write_bundle([{"kind": "bench", "wallNs": 0, "monoNs": 0,
                          "fields": {}}])
        ms = (time.perf_counter() - t0) * 1000.0
        return ms, ring_bytes
    finally:
        bb.close()
        _shutil.rmtree(d, ignore_errors=True)


def _git_sha() -> str:
    import subprocess

    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — benching outside a checkout
        return "unknown"


def append_history(row: dict) -> None:
    """Persist one bench-main run into benchmarks/history/ (git sha +
    backend + timestamp stamped), the append-only record
    scripts/bench_trend.py reads — so the BENCH_r* files stop being
    dead weight and every future run extends a readable trajectory."""
    import os

    import jax

    hist_dir = os.path.join("benchmarks", "history")
    try:
        os.makedirs(hist_dir, exist_ok=True)
        stamped = {
            "time": time.time(),
            "git_sha": _git_sha(),
            "backend": jax.default_backend(),
            **row,
        }
        name = time.strftime("%Y%m%d-%H%M%S") + f"-{stamped['git_sha']}.json"
        with open(os.path.join(hist_dir, name), "w") as f:
            json.dump(stamped, f, indent=1)
        print(f"bench: appended {os.path.join(hist_dir, name)}", file=sys.stderr)
    except OSError as e:  # noqa: BLE001 — history is best-effort
        print(f"bench: history append failed: {e}", file=sys.stderr)


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@contextlib.contextmanager
def _bench_daemon(extra_env=None, extra_env_fn=None, what="bench daemon"):
    """Spawn one CPU-pinned daemon subprocess (the loopback rule: the
    receiver needs its OWN GIL) on fresh ports, wait for its listening
    line, and SIGTERM/kill it on exit — the harness every loopback
    measurement shares.  Yields (http_port, grpc_port).
    `extra_env_fn(http_port, grpc_port)` builds overrides that need the
    allocated ports (e.g. a GUBER_STATIC_PEERS naming both daemons);
    plain `extra_env` overrides apply last."""
    import os
    import signal
    import subprocess

    http_port, grpc_port = _free_port(), _free_port()
    env = dict(os.environ)
    env.update(
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=os.path.join(os.getcwd(), ".jax_cache"),
        GUBER_HTTP_ADDRESS=f"127.0.0.1:{http_port}",
        GUBER_GRPC_ADDRESS=f"127.0.0.1:{grpc_port}",
        GUBER_STATIC_PEERS=f"127.0.0.1:{grpc_port}|127.0.0.1:{http_port}",
        GUBER_GLOBAL_SYNC_WAIT="3600s",
        GUBER_MULTI_REGION_SYNC_WAIT="3600s",
        GUBER_BATCH_TIMEOUT="30s",
        GUBER_CACHE_SIZE="8192",
    )
    if extra_env_fn is not None:
        env.update(extra_env_fn(http_port, grpc_port))
    if extra_env:
        env.update(extra_env)
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.server"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=os.getcwd(),
    )
    try:
        line = proc.stdout.readline()
        if "listening" not in line:
            raise RuntimeError(f"{what} failed to start: {line!r}")
        yield http_port, grpc_port
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def measure_snapshot(lanes: int = 131_072, batch: int = 16_384,
                     timeout_s: float = 120.0):
    """Durability-plane dump + restore wall time at the 131k-lane
    batch size, measured against REAL daemons in their own processes
    (the PR 8 loopback harness):

      1. spawn daemon A with GUBER_SNAPSHOT on a short interval,
         populate `lanes` distinct buckets through the columnar front
         door, and read the daemon's own dump timing
         (`/debug/status` snapshot.lastSaveSeconds — the in-process
         gather+encode+fsync wall time, wire excluded) once a
         completed snapshot covers every lane;
      2. SIGTERM A (final snapshot), spawn daemon B on the same file,
         and read snapshot.lastRestoreSeconds — the boot-time
         read+verify+ONE-merge-commit wall time.

    Returns {"dump_s", "restore_s", "lanes", "bytes"}.  The restore
    row gates (snapshot_restore_ms ceiling): boot recovery is on the
    deploy critical path, and an accidentally per-item restore would
    show up here as a ~100x blowup."""
    import json as _json
    import os
    import tempfile
    import urllib.request

    from gubernator_tpu.client import ColumnsV1Client

    def _status(port):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/status", timeout=10
        ) as f:
            return _json.loads(f.read())["snapshot"]

    tmp = tempfile.mkdtemp(prefix="gub_bench_snap_")
    path = os.path.join(tmp, "bench.snap")
    env = {
        "GUBER_SNAPSHOT": path,
        "GUBER_SNAPSHOT_INTERVAL": "1s",
        "GUBER_NATIVE_HTTP": "1",
        "GUBER_INGRESS_COLUMNS": "1",
        # Two CPU devices: lanes/2 per shard, pow2-padded.
        "GUBER_CACHE_SIZE": str(lanes * 2),
        "GUBER_WARMUP_SHAPES": "1,1000",
    }
    with _bench_daemon(extra_env=env, what="snapshot daemon A") as (hp, _gp):
        client = ColumnsV1Client(f"127.0.0.1:{hp}", timeout_s=60.0)
        try:
            for lo in range(0, lanes, batch):
                n = min(batch, lanes - lo)
                client.submit_columns((
                    ["bench"] * n,
                    [f"snap:{lo + i}" for i in range(n)],
                    np.zeros(n, np.int32),
                    np.zeros(n, np.int32),
                    np.ones(n, np.int64),
                    np.full(n, 1_000_000, np.int64),
                    np.full(n, 3_600_000, np.int64),
                )).result(timeout=60)
        finally:
            client.close()
        # Wait for a save that STARTED after ingestion finished, so
        # its gather covers every lane (savedLanes is cumulative
        # across saves and cannot prove that by itself).
        base = _status(hp)["savesOk"]
        deadline = time.monotonic() + timeout_s
        dump_s = None
        while time.monotonic() < deadline:
            s = _status(hp)
            if s["savesOk"] > base + 1:
                dump_s = s["lastSaveSeconds"]
                break
            time.sleep(0.25)
        if dump_s is None:
            raise RuntimeError("daemon A never completed a full snapshot")
    size = os.path.getsize(path)
    with _bench_daemon(extra_env=env, what="snapshot daemon B") as (hp, _gp):
        s = _status(hp)
        if s["restore"] != "ok" or s["restoredLanes"] < lanes:
            raise RuntimeError(
                f"daemon B restore {s['restore']!r}, "
                f"{s['restoredLanes']}/{lanes} lanes"
            )
        restore_s = s["lastRestoreSeconds"]
    return {
        "dump_s": dump_s, "restore_s": restore_s,
        "lanes": lanes, "bytes": size,
    }


def measure_peer_forward(mode: str = "columns", n_threads: int = 8,
                         iters: int = 4, batch: int = 1000) -> float:
    """Loopback two-daemon forward throughput: the owner daemon runs in
    its OWN process (own GIL, as in production) and the entry daemon
    here forwards every lane of every batch to it — the whole request
    crosses the peer hop.  `mode`: "columns" = the columnar wire path
    (proto columns / binary frame, wire.py "columnar peer hop");
    "classic" = GUBER_PEER_COLUMNS=0 on both sides, i.e. the
    per-request JSON/protobuf encoding of a pre-columns build.

    Both daemons are pinned to CPU devices: this row gates the WIRE
    path's software cost — the device kernel has its own rows, and
    tunnel weather must not leak into a loopback-RPC verdict.
    Returns checks/s (best of 3 epochs)."""
    import threading

    import jax

    from gubernator_tpu.cluster import fast_test_behaviors
    from gubernator_tpu.config import DaemonConfig
    from gubernator_tpu.daemon import Daemon
    from gubernator_tpu.service import IngressColumns
    from gubernator_tpu.types import PeerInfo

    behaviors = fast_test_behaviors()
    behaviors.peer_columns = mode == "columns"
    behaviors.global_sync_wait_s = 3600.0
    behaviors.multi_region_sync_wait_s = 3600.0
    behaviors.batch_timeout_s = 30.0

    cpu_devices = jax.devices("cpu")
    entry = Daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=8192,
            global_cache_size=256,
            behaviors=behaviors,
            peer_discovery_type="static",
            devices=cpu_devices,
        )
    ).start()

    try:
        with _bench_daemon(
            extra_env_fn=lambda h, g: {
                "GUBER_STATIC_PEERS": (
                    f"127.0.0.1:{g}|127.0.0.1:{h},"
                    f"{entry.peer_info.grpc_address}|"
                    f"{entry.peer_info.http_address}"
                ),
                "GUBER_PEER_COLUMNS": "1" if mode == "columns" else "0",
            },
            what="owner daemon",
        ) as (owner_http, owner_grpc):
            entry.set_peers([
                entry.peer_info,
                PeerInfo(
                    grpc_address=f"127.0.0.1:{owner_grpc}",
                    http_address=f"127.0.0.1:{owner_http}",
                ),
            ])

            keys = []
            i = 0
            while len(keys) < batch:
                k = f"fw{i}"
                if not entry.service.get_peer(f"bench_{k}").info.is_owner:
                    keys.append(k)
                i += 1

            def cols():
                return IngressColumns(
                    names=["bench"] * batch,
                    unique_keys=list(keys),
                    algorithm=np.zeros(batch, np.int32),
                    behavior=np.zeros(batch, np.int32),
                    hits=np.ones(batch, np.int64),
                    limit=np.full(batch, 1_000_000, np.int64),
                    duration=np.full(batch, 3_600_000, np.int64),
                )

            first = entry.service.get_rate_limits_columns(cols()).response_at(0)
            if first.error or not first.metadata.get("owner"):
                raise RuntimeError(f"forwarded warmup failed: {first}")

            def worker():
                for _ in range(iters):
                    entry.service.get_rate_limits_columns(cols())

            def epoch():
                ts = [
                    threading.Thread(target=worker)
                    for _ in range(n_threads)
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()

            epoch()  # warm: pad-bucket compiles, window negotiation
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                epoch()
                dt = time.perf_counter() - t0
                best = max(best, batch * iters * n_threads / dt)
            return best
    finally:
        entry.close()


def measure_global_plane(mode: str = "columns", n_threads: int = 2,
                         iters: int = 3, batch: int = 512):
    """Loopback GLOBAL replication-plane throughput: the receiver
    daemon runs in its OWN process (own GIL, as in production — the
    measure_peer_forward technique) and this process plays the owner's
    GlobalManager, driving both host-tier legs against it:

      * broadcast — UpdatePeerGlobals of `batch` keys per send.
        "columns": a fresh wire.BroadcastBatch per send (the per-tick
        encode; the encode-ONCE win is across peers) negotiated onto
        the columnar wire, committed by the receiver as ONE replica
        scatter.  "classic": the legacy per-item encoding against a
        GUBER_GLOBAL_COLUMNS=0 receiver — per-item wire AND one replica
        dispatch per item, the whole pre-columns plane.
      * forwarded hits — `batch` GLOBAL lanes per GetPeerRateLimits
        send, columnar vs classic per-request encoding.

    Both daemons CPU-pinned (wire/dispatch cost, not device weather).
    Returns a dict with broadcast_items_per_sec, forwarded_hits_per_sec
    and the combined plane_items_per_sec (total items over the two
    legs' best-epoch wall time) that the same-run
    global_plane_vs_classic gate ratio uses."""
    import threading

    from gubernator_tpu import wire
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.parallel.global_mgr import GlobalsColumns
    from gubernator_tpu.peer_client import PeerClient
    from gubernator_tpu.types import (
        Behavior,
        GetRateLimitsRequest,
        PeerInfo,
        RateLimitRequest,
    )

    columns = mode == "columns"
    with contextlib.ExitStack() as stack:
        owner_http, owner_grpc = stack.enter_context(_bench_daemon(
            extra_env={
                "GUBER_GLOBAL_COLUMNS": "1" if columns else "0",
                "GUBER_PEER_COLUMNS": "1" if columns else "0",
                "GUBER_GLOBAL_CACHE_SIZE": "4096",
            },
            what="receiver daemon",
        ))
        behaviors = BehaviorConfig(
            batch_timeout_s=30.0,
            peer_columns=columns,
            global_columns=columns,
        )
        client = PeerClient(
            PeerInfo(
                grpc_address=f"127.0.0.1:{owner_grpc}",
                http_address=f"127.0.0.1:{owner_http}",
            ),
            behaviors,
        )
        # LIFO: the client drains before the daemon it talks to exits.
        stack.callback(client.shutdown, timeout_s=2.0)
        now = int(time.time() * 1000)
        bcols = GlobalsColumns(
            keys=[f"gp_bench:{i}" for i in range(batch)],
            algorithm=np.zeros(batch, np.int32),
            status=np.zeros(batch, np.int32),
            limit=np.full(batch, 1_000_000, np.int64),
            remaining=np.full(batch, 999_999, np.int64),
            reset_time=np.full(batch, now + 3_600_000, np.int64),
        )
        # Classic leg sends the EXACT pre-columns payloads: the
        # dataclass list through the legacy per-item API (the sync pass
        # built these once per tick pre-PR too).
        updates = bcols.to_updates()
        hit_pc = (
            ["gp"] * batch,
            [f"bench:{i}" for i in range(batch)],
            np.zeros(batch, np.int32),
            np.full(batch, int(Behavior.GLOBAL), np.int32),
            np.ones(batch, np.int64),
            np.full(batch, 1_000_000, np.int64),
            np.full(batch, 3_600_000, np.int64),
        )
        hit_reqs = GetRateLimitsRequest(
            requests=[
                RateLimitRequest(
                    name="gp", unique_key=f"bench:{i}", hits=1,
                    limit=1_000_000, duration=3_600_000,
                    behavior=Behavior.GLOBAL,
                )
                for i in range(batch)
            ]
        )

        def send_broadcast():
            if columns:
                client.update_peer_globals_batch(
                    wire.BroadcastBatch(bcols), timeout_s=30.0
                )
            else:
                client.update_peer_globals(updates, timeout_s=30.0)

        def send_hits():
            if columns:
                client.send_columns_direct(hit_pc, timeout_s=30.0)
            else:
                client.get_peer_rate_limits(hit_reqs, timeout_s=30.0)

        def run_leg(send, epochs: int = 3):
            def worker():
                for _ in range(iters):
                    send()

            send()  # warm: negotiation + receiver pad-bucket compiles
            best_rate, best_dt = 0.0, float("inf")
            for _ in range(epochs):
                ts = [
                    threading.Thread(target=worker) for _ in range(n_threads)
                ]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                dt = time.perf_counter() - t0
                rate = batch * iters * n_threads / dt
                if rate > best_rate:
                    best_rate, best_dt = rate, dt
            return best_rate, best_dt

        bc_rate, bc_dt = run_leg(send_broadcast)
        hit_rate, hit_dt = run_leg(send_hits)
        total = 2 * batch * iters * n_threads
        return {
            "broadcast_items_per_sec": bc_rate,
            "forwarded_hits_per_sec": hit_rate,
            "plane_items_per_sec": total / (bc_dt + hit_dt),
        }


def measure_region_plane(mode: str = "columns", n_threads: int = 4,
                         iters: int = 2, batch: int = 4096) -> float:
    """Loopback cross-region federation-plane throughput
    (federation.py): the remote region's owner daemon runs in its OWN
    process (own GIL, as in production — the measure_peer_forward
    rule) and this process plays the origin region's FederationManager
    flush, driving one federation.RegionBatch per send at it:

      * "columns" — region_columns=True against a
        GUBER_REGION_COLUMNS=1 receiver: ONE GUBC kind-7 frame per
        flush, decoded and applied as ONE columnar batch.
      * "classic" — region_columns=False against a
        GUBER_REGION_COLUMNS=0 receiver (exactly a pre-federation
        peer): the sticky per-item GetPeerRateLimits chunk train,
        per-item decode into the receive path — the whole pre-PR
        plane, no probe burned (the knob pins the client classic).

    A FRESH RegionBatch per send reproduces the per-flush encode (the
    encode-ONCE win is across the region fan-out, not across
    flushes), and `batch` is sized like a production flush (thousands
    of aggregated keys): the classic wire's 1000-item per-RPC cap
    (behaviors.batch_limit) forces a chunk train there while ONE
    kind-7 frame carries the whole flush — at small batches both fit
    one RPC and the ratio collapses to transport noise (measured 0.97
    at 512 vs 4.65 at 4096 on the 2-core dev box).  Both daemons
    CPU-pinned (wire/decode cost, not device weather).  Returns
    key-lanes/s over the best epoch; the same-run
    region_plane_vs_classic gate ratio divides the two modes."""
    import threading

    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.federation import RegionBatch, RegionColumns
    from gubernator_tpu.peer_client import PeerClient
    from gubernator_tpu.types import PeerInfo

    columns = mode == "columns"
    with contextlib.ExitStack() as stack:
        owner_http, owner_grpc = stack.enter_context(_bench_daemon(
            extra_env={
                "GUBER_REGION_COLUMNS": "1" if columns else "0",
                "GUBER_DATA_CENTER": "bench-remote",
            },
            what="remote-region daemon",
        ))
        behaviors = BehaviorConfig(
            batch_timeout_s=30.0, region_columns=columns
        )
        client = PeerClient(
            PeerInfo(
                grpc_address=f"127.0.0.1:{owner_grpc}",
                http_address=f"127.0.0.1:{owner_http}",
            ),
            behaviors,
        )
        # LIFO: the client drains before the daemon it talks to exits.
        stack.callback(client.shutdown, timeout_s=2.0)
        cols = RegionColumns(
            origin="bench-origin",
            names=["rp"] * batch,
            unique_keys=[f"bench:{i}" for i in range(batch)],
            algorithm=np.zeros(batch, np.int32),
            behavior=np.zeros(batch, np.int32),
            hits=np.ones(batch, np.int64),
            limit=np.full(batch, 1_000_000, np.int64),
            duration=np.full(batch, 3_600_000, np.int64),
        )

        def send():
            # Fresh batch = fresh encode caches, the per-flush cost.
            client.update_region_columns(RegionBatch(cols), timeout_s=30.0)

        def worker():
            for _ in range(iters):
                send()

        send()  # warm: negotiation + receiver pad-bucket compiles
        best_rate = 0.0
        for _ in range(3):
            ts = [threading.Thread(target=worker) for _ in range(n_threads)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t0
            best_rate = max(best_rate, batch * iters * n_threads / dt)
        return best_rate


def measure_ingress_columns(mode: str = "columns", n_threads: int = 8,
                            iters: int = 8, batch: int = 1000) -> float:
    """Public-ingress throughput over the REAL wire against a daemon in
    its OWN process (own GIL — the established loopback rule; the
    daemon runs the native epoll edge, CPU-pinned devices).  `mode`:

      * "columns" — ColumnsV1Client: client-side column accumulation,
        GUBC kind-5 frames (pipelined), native gt_frame_parse decode on
        the daemon, kind-6 array responses.  The front-door fast path.
      * "json" — the classic V1Client per-request JSON encoding against
        the SAME daemon build: per-request dict/dataclass work both
        sides, json.loads/render on the daemon.  The pre-PR client
        wire (keep-alive included, so the ratio measures the ENCODING,
        not reconnect overhead).

    Both modes measured back-to-back in the same bench run so host
    weather cancels in the ingress_columns_vs_json gate ratio.
    Returns checks/s (best of 3 epochs)."""
    import threading

    from gubernator_tpu.client import ColumnsV1Client, V1Client
    from gubernator_tpu.types import GetRateLimitsRequest, RateLimitRequest

    closers = []
    with _bench_daemon(
        extra_env={
            "GUBER_NATIVE_HTTP": "1",
            "GUBER_INGRESS_COLUMNS": "1",
            "GUBER_CACHE_SIZE": "32768",
        },
        what="ingress daemon",
    ) as (http_port, _grpc_port):
        endpoint = f"127.0.0.1:{http_port}"
        if mode == "columns":
            client = ColumnsV1Client(endpoint, timeout_s=30.0)
            closers.append(client)
            per_thread = [
                (
                    ["bench"] * batch,
                    [f"ic{t}:{i}" for i in range(batch)],
                    (np.arange(batch) % 2).astype(np.int32),
                    np.zeros(batch, np.int32),
                    np.ones(batch, np.int64),
                    np.full(batch, 1_000_000, np.int64),
                    np.full(batch, 3_600_000, np.int64),
                )
                for t in range(n_threads)
            ]

            def one(t):
                client.submit_columns(per_thread[t]).result(timeout=60)
        else:
            clients = [V1Client(endpoint, timeout_s=30.0)
                       for _ in range(n_threads)]
            closers.extend(clients)
            per_thread = [
                GetRateLimitsRequest(requests=[
                    RateLimitRequest(
                        name="bench", unique_key=f"ic{t}:{i}", hits=1,
                        limit=1_000_000, duration=3_600_000,
                        algorithm=i % 2,
                    )
                    for i in range(batch)
                ])
                for t in range(n_threads)
            ]

            def one(t):
                clients[t].get_rate_limits(per_thread[t])

        def worker(t):
            for _ in range(iters):
                one(t)

        def epoch():
            ts = [
                threading.Thread(target=worker, args=(t,))
                for t in range(n_threads)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()

        try:
            epoch()  # warm: pad-bucket compiles, negotiation, keep-alives
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                epoch()
                dt = time.perf_counter() - t0
                best = max(best, batch * iters * n_threads / dt)
            return best
        finally:
            # Clients drain before the daemon context tears down.
            for c in closers:
                c.close()


def measure_native_ingress(conns: int = 8, depth: int = 10,
                           batch: int = 4096, dup: int = 4,
                           window_s: float = 3.0, quads: int = 2) -> dict:
    """Native-service-loop ingress throughput over the REAL wire, BOTH
    legs in one run: a GUBER_NATIVE_INGRESS=1 daemon (the GIL-free loop
    — accept -> kind-5 validate -> FNV-1 hash + ring route -> coalesce
    -> one Python dispatch per batch -> kind-6 fill -> write) and a
    GUBER_NATIVE_INGRESS=0 daemon (exactly the PR 8 Python-assembled
    edge), each in its OWN subprocess (the loopback GIL rule) with
    GUBER_ACCEPTORS=2, alive SIMULTANEOUSLY and driven ALTERNATELY in
    ABBA quads — host weather drifts cancel inside a quad instead of
    landing on whichever leg ran second (the PR 12 _overhead_pairs
    discipline), which is what makes native_vs_pr8_ratio trustworthy on
    a weather-prone box.

    The driver is deliberately client-cost-free: each connection
    pipelines ONE pre-encoded `batch`-lane frame `depth` deep and just
    counts responses, so both legs measure the SERVER.  The workload is
    the HOT-WINDOW shape the columnar client produces under load — each
    frame carries `batch` checks over batch/dup distinct keys (`dup`
    concurrent callers per key coalesced into one window flush, the
    reference's thundering-herd case and the analytic-duplicate
    kernel's reason to exist), and the deep pipeline keeps many frames
    pending so the native ring coalesces them into device-ceiling
    takes.

    Returns {"checks_per_s" (best native window), "noise"
    (best-vs-median half-gap), "pr8_checks_per_s", "ratio" (median
    per-quad ratio), "ratio_noise" (quad half-spread),
    "steady_recompiles" (native daemon, during the timed windows; None
    if the telemetry plane is absent), "audit_violations"}."""
    import contextlib
    import json as _json
    import socket
    import threading
    import urllib.request

    from gubernator_tpu import wire

    base_env = {
        "GUBER_NATIVE_HTTP": "1",
        "GUBER_ACCEPTORS": "2",
        "GUBER_INGRESS_COLUMNS": "1",
        "GUBER_CACHE_SIZE": "262144",
        # The pipelined in-flight lanes (conns x depth x batch = 327k)
        # must fit the shed bound — this bench measures throughput, not
        # the 429 path (tests/test_native_loop.py covers shed parity).
        "GUBER_INGRESS_QUEUE_LANES": "524288",
        # A 4-way virtual mesh pipelines measurably better than the
        # harness default 2 on this box at device-ceiling takes
        # (smaller per-shard pads + deeper inter-op overlap: +12%
        # measured; both legs get the same config so the ratio is
        # untouched).
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        # Pad LADDER: takes are 1-15 frames of `batch` lanes over the 4
        # CPU shards (per-shard m = take/4 -> pow2 pads 1024..16384), so
        # force-warm EVERY bucket a take can land in — a weather-starved
        # window can shrink a take to one frame, and any compile during
        # the timed windows is shape churn the steady_recompiles row
        # must catch, not pay.
        "GUBER_WARMUP_SHAPES": "1,1000,4096,8192,16384,32768,60000",
        "GUBER_AUDIT_INTERVAL": "1s",
    }

    def _debug(port: int, path: str) -> dict:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/{path}", timeout=10
        ) as f:
            return _json.loads(f.read())

    payloads = []
    for t in range(conns):
        frame = wire.encode_ingress_frame((
            ["bench"] * batch,
            [f"ni{t}:{i // dup}" for i in range(batch)],
            # Algorithm alternates per KEY (constant inside a duplicate
            # group — mixed configs would demote the group off the
            # analytic round-0 path).
            (np.arange(batch) // dup % 2).astype(np.int32),
            np.zeros(batch, np.int32),
            np.ones(batch, np.int64),
            np.full(batch, 1_000_000_000, np.int64),
            np.full(batch, 3_600_000, np.int64),
        ))
        payloads.append((
            f"POST /v1/GetRateLimits HTTP/1.1\r\nHost: b\r\n"
            f"Content-Type: {wire.COLUMNS_CONTENT_TYPE}\r\n"
            f"Content-Length: {len(frame)}\r\n\r\n"
        ).encode() + frame)

    def _window(port: int, timed_s: float) -> float:
        """One driver session: connect, fill the pipeline, settle, time
        a mid-stream window, tear down.  Returns checks/s."""
        stop = threading.Event()
        counts = [0] * conns
        errors: list = []

        def run_conn(t: int) -> None:
            try:
                s = socket.create_connection(("127.0.0.1", port),
                                             timeout=60.0)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                rf = s.makefile("rb")
                payload = payloads[t]
                try:
                    for _ in range(depth):
                        s.sendall(payload)
                    while not stop.is_set():
                        line = rf.readline()
                        if not line.startswith(b"HTTP/1.1 200"):
                            raise RuntimeError(f"bad response: {line!r}")
                        clen = 0
                        while True:
                            h = rf.readline()
                            if h in (b"\r\n", b"\n", b""):
                                break
                            if h.lower().startswith(b"content-length"):
                                clen = int(h.split(b":")[1])
                        body = rf.read(clen)
                        if len(body) != clen or body[:4] != b"GUBC":
                            raise RuntimeError("truncated/non-frame body")
                        counts[t] += 1
                        s.sendall(payload)
                finally:
                    rf.close()
                    s.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                stop.set()

        threads = [
            threading.Thread(target=run_conn, args=(t,)) for t in range(conns)
        ]
        for t in threads:
            t.start()
        time.sleep(0.8)  # pipeline fill + settle
        c0 = sum(counts)
        t0 = time.perf_counter()
        time.sleep(timed_s)
        dt = time.perf_counter() - t0
        rate = (sum(counts) - c0) * batch / dt
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        if errors:
            raise RuntimeError(f"native ingress driver failed: {errors[0]}")
        return rate

    with contextlib.ExitStack() as stack:
        native_port, _ = stack.enter_context(_bench_daemon(
            extra_env={**base_env, "GUBER_NATIVE_INGRESS": "1"},
            what="native ingress daemon (native)",
        ))
        # Phase A — the ABSOLUTE row, native daemon SOLE resident (the
        # deployed shape: one daemon owns the box): warm, then timed
        # windows.
        _window(native_port, window_s)  # warm: residual pads, caches
        try:
            rc0 = _debug(native_port, "device").get("steadyRecompiles")
        except Exception:  # noqa: BLE001 — plane off
            rc0 = None
        rates = {"native": [], "pr8": []}
        for _ in range(3):
            rates["native"].append(_window(native_port, window_s))
        # Phase B — the RATIO: bring up the PR 8 leg beside it and
        # alternate ABBA quads so weather drift cancels inside a quad.
        pr8_port, _ = stack.enter_context(_bench_daemon(
            extra_env={**base_env, "GUBER_NATIVE_INGRESS": "0"},
            what="native ingress daemon (pr8)",
        ))
        ports = {"native": native_port, "pr8": pr8_port}
        _window(pr8_port, window_s)  # warm the PR 8 leg
        quad_ratios = []
        quad_rates = {"native": [], "pr8": []}
        for q in range(quads):
            order = (
                ("native", "pr8", "pr8", "native") if q % 2 == 0
                else ("pr8", "native", "native", "pr8")
            )
            quad = {"native": [], "pr8": []}
            for leg in order:
                r = _window(ports[leg], window_s)
                quad_rates[leg].append(r)
                quad[leg].append(r)
            quad_ratios.append(
                (sum(quad["native"]) / 2.0) / max(sum(quad["pr8"]) / 2.0, 1.0)
            )
        rates["pr8"] = quad_rates["pr8"]
        steady = None
        if rc0 is not None:
            try:
                steady = (
                    _debug(native_port, "device")["steadyRecompiles"] - rc0
                )
            except Exception:  # noqa: BLE001
                steady = None
        # Let the 1s auditor reconcile the final window, then read the
        # violation total — the ledger must stay balanced at rate.
        time.sleep(2.5)
        violations = _debug(native_port, "audit")["violationTotal"]

    nat = sorted(rates["native"])
    best = nat[-1]
    quad_ratios.sort()
    ratio = quad_ratios[len(quad_ratios) // 2]
    return {
        # Noise = the best window's half-gap to the median: the row is
        # a best-of (one clean multi-second window demonstrates the
        # sustainable rate); the gate's noise-adjusted verdict turns a
        # weather dip into an inconclusive SKIP, never a silent flip.
        "checks_per_s": best,
        "noise": (best - nat[len(nat) // 2]) / 2.0,
        "pr8_checks_per_s": max(rates["pr8"]),
        "ratio": ratio,
        "ratio_noise": (quad_ratios[-1] - quad_ratios[0]) / 2.0,
        "steady_recompiles": steady,
        "audit_violations": violations,
    }


def measure_express_latency(conns: int = 4, window_s: float = 3.0,
                            windows: int = 3) -> dict:
    """Express-lane request latency over the REAL wire: one native-edge
    daemon (GUBER_EXPRESS on — the shipped default — with
    GUBER_LATENCY_TARGET_MS=10 so the window cap binds), driven by
    `conns` CLOSED-LOOP clients each cycling ONE single-lane
    NO_BATCHING kind-5 frame (depth 1: send, wait for the answer, send
    again — the interactive shape).  This is exactly the traffic class
    the express lane exists for: shallow queue, singleton checks,
    latency-flagged.  Pre-express, every one of these frames fell back
    to the Python path and a windowed dispatch (p50 ~100-250 ms under
    load); the lane routes them native-express -> immediate dispatch ->
    the host scalar slot, so the row's ceiling is single-digit ms.

    Every request's wall time is sampled client-side; the row reports
    the MEDIAN window's p50/p99 with the cross-window half-spread as
    noise (a weather-hit window reads as an honest noise-adjusted SKIP
    at the gate, never a silent flip).  The daemon's steady-recompile
    and audit-violation counts ride along: the latency is only real if
    no express hit compiled a program and the conservation ledger
    stayed balanced.

    Returns {"p50_ms", "p99_ms", "noise_ms", "n_samples",
    "checks_per_s", "express_frames", "steady_recompiles",
    "audit_violations"}."""
    import contextlib
    import json as _json
    import socket
    import threading
    import urllib.request

    from gubernator_tpu import wire

    env = {
        "GUBER_NATIVE_HTTP": "1",
        "GUBER_NATIVE_INGRESS": "1",
        "GUBER_EXPRESS": "1",
        "GUBER_LATENCY_TARGET_MS": "10",
        "GUBER_AUDIT_INTERVAL": "1s",
    }

    def _debug(port: int, path: str) -> dict:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/{path}", timeout=10
        ) as f:
            return _json.loads(f.read())

    payloads = []
    for t in range(conns):
        frame = wire.encode_ingress_frame((
            ["bench"],
            [f"xl{t}"],
            np.array([t % 2], np.int32),      # token and leaky both
            np.array([1], np.int32),          # Behavior.NO_BATCHING
            np.ones(1, np.int64),
            np.full(1, 1_000_000_000, np.int64),
            np.full(1, 3_600_000, np.int64),
        ))
        payloads.append((
            f"POST /v1/GetRateLimits HTTP/1.1\r\nHost: b\r\n"
            f"Content-Type: {wire.COLUMNS_CONTENT_TYPE}\r\n"
            f"Content-Length: {len(frame)}\r\n\r\n"
        ).encode() + frame)

    def _window(port: int, timed_s: float) -> list:
        """One driver session: closed-loop singles, per-request wall
        times (seconds) from all connections pooled."""
        stop = threading.Event()
        samples: list = [[] for _ in range(conns)]
        errors: list = []

        def run_conn(t: int) -> None:
            try:
                s = socket.create_connection(("127.0.0.1", port),
                                             timeout=30.0)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                rf = s.makefile("rb")
                payload = payloads[t]
                try:
                    while not stop.is_set():
                        t0 = time.perf_counter()
                        s.sendall(payload)
                        line = rf.readline()
                        if not line.startswith(b"HTTP/1.1 200"):
                            raise RuntimeError(f"bad response: {line!r}")
                        clen = 0
                        while True:
                            h = rf.readline()
                            if h in (b"\r\n", b"\n", b""):
                                break
                            if h.lower().startswith(b"content-length"):
                                clen = int(h.split(b":")[1])
                        body = rf.read(clen)
                        if len(body) != clen or body[:4] != b"GUBC":
                            raise RuntimeError("truncated/non-frame body")
                        samples[t].append(time.perf_counter() - t0)
                finally:
                    rf.close()
                    s.close()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                stop.set()

        threads = [
            threading.Thread(target=run_conn, args=(t,)) for t in range(conns)
        ]
        for th in threads:
            th.start()
        time.sleep(timed_s)
        stop.set()
        for th in threads:
            th.join(timeout=10.0)
        if errors:
            raise RuntimeError(f"express latency driver failed: {errors[0]}")
        return [x for per in samples for x in per]

    with contextlib.ExitStack() as stack:
        port, _ = stack.enter_context(_bench_daemon(
            extra_env=env, what="express latency daemon",
        ))
        # Warm: conn setup, first takes, the scalar capability probe,
        # AND the GlobalManager's first sync tick (~1s after start —
        # its collective compiles and holds the store lock for ~1s,
        # which must not land inside a timed window).
        _window(port, 2.5)
        try:
            rc0 = _debug(port, "device").get("steadyRecompiles")
        except Exception:  # noqa: BLE001 — plane off
            rc0 = None
        per_window = []
        total_n, total_s = 0, 0.0
        for _ in range(windows):
            t0 = time.perf_counter()
            vals = sorted(_window(port, window_s))
            total_n += len(vals)
            total_s += time.perf_counter() - t0
            per_window.append((
                percentile(vals, 0.50) * 1e3,
                percentile(vals, 0.99) * 1e3,
                len(vals),
            ))
        steady = None
        if rc0 is not None:
            try:
                steady = _debug(port, "device")["steadyRecompiles"] - rc0
            except Exception:  # noqa: BLE001
                steady = None
        # Hit-rate proof: these frames must have ridden the native
        # express queue, not the Python fallback.
        express_frames = (
            _debug(port, "status")["express"]["lanes"].get("native", 0)
        )
        time.sleep(2.5)  # let the 1s auditor reconcile the last window
        violations = _debug(port, "audit")["violationTotal"]

    p50s = sorted(w[0] for w in per_window)
    p99s = sorted(w[1] for w in per_window)
    mid = len(per_window) // 2
    return {
        "p50_ms": p50s[mid],
        "p99_ms": p99s[mid],
        # Cross-window half-spread: the honest between-window weather
        # band for the noise-adjusted ceiling verdicts.
        "noise_ms": (p99s[-1] - p99s[0]) / 2.0,
        "p50_noise_ms": (p50s[-1] - p50s[0]) / 2.0,
        "n_samples": min(w[2] for w in per_window),
        "checks_per_s": total_n / max(total_s, 1e-9),
        "express_frames": express_frames,
        "steady_recompiles": steady,
        "audit_violations": violations,
    }


GATE_THRESHOLDS = "benchmarks/gate_thresholds.json"
LAST_DEVICE_ROWS = "benchmarks/last_device_rows.json"


def _save_device_rows(dev, extra=None) -> None:
    """Persist main()'s device rows so a follow-up `--gate` (the `make
    bench` sequence) can evaluate thresholds without re-paying the
    whole differential measurement on the tunnel."""
    import jax

    rows = {
        "time": time.time(),
        # The gate keys tunnel-calibrated device ceilings on this:
        # rows measured on a CPU box must SKIP them, not FAIL.
        "backend": jax.default_backend(),
        "device_batch_us": dev["device_batch_us"],
        "device_us_b1024": dev["small_batch_us"][1024][0],
        "device_us_b256": dev["small_batch_us"][256][0],
        "below_floor": {
            f"device_us_b{sb}": dev["small_batch_us"][sb][2]
            for sb in (256, 1024)
        },
        # Per-row measurement noise (us): the gate evaluates
        # NOISE-ADJUSTED bounds, so a small-batch row whose point
        # estimate is timer noise still yields a trustworthy verdict
        # (value+noise under the limit = PASS) instead of a skip.
        "noise": {
            f"device_us_b{sb}": dev["small_batch_us"][sb][3]
            for sb in (256, 1024)
        },
    }
    if extra:
        extra = dict(extra)
        # Per-row noise riding along with non-device rows (the native
        # ingress windows' spread): merged into the shared noise dict
        # the gate's noise-adjusted verdicts read.
        rows["noise"].update(extra.pop("extra_noise", {}))
        rows.update(extra)
    with open(LAST_DEVICE_ROWS, "w") as f:
        json.dump(rows, f)


def gate_verdict(value: float, spec: dict, noise: float = 0.0):
    """Noise-adjusted gate verdict for one row: ("PASS"|"FAIL"|"SKIP",
    limit).  fail_above rows pass when even value+noise is under the
    limit and fail when even value-noise exceeds it; a noise band
    straddling the limit is inconclusive (SKIP) — so timer noise can
    never flip a verdict, which is what makes the row trustworthy
    (round-5's b256 fired below_floor on noise_us 77 vs value 4.7;
    4.7+77 is still far under the 250 limit, a clean PASS).

    Ceiling rows come in two spellings: the historical `fail_above_us`
    (device rows, µs) and the generic `fail_above` (lower-is-better in
    the row's own unit — the ingress latency-ms ceilings)."""
    if "fail_above_us" in spec or "fail_above" in spec:
        limit = spec.get("fail_above_us", spec.get("fail_above"))
        if value + noise <= limit:
            return "PASS", limit
        if value - noise > limit:
            return "FAIL", limit
        return "SKIP", limit
    limit = spec["fail_below"]
    if value - noise >= limit:
        return "PASS", limit
    if value + noise < limit:
        return "FAIL", limit
    return "SKIP", limit


def gate() -> int:
    """Failing regression gate on the stable device rows.

    Evaluates device_batch_us (131k batch), the small-batch rows, the
    dispatch_overlap_ratio (device_batch_us /
    dispatch_batch_us_incl_tunnel — how much of the dispatch path's
    cost the overlapped pipeline hides behind device compute), and the
    ingress/peer-forward throughput rows, against pinned thresholds.
    Verdicts are NOISE-ADJUSTED (gate_verdict): a noise band straddling
    the limit is inconclusive, never a flip.  Reuses the rows a
    bench-main run just measured (benchmarks/last_device_rows.json,
    <1h old) instead of re-measuring; measures fresh otherwise.  Exit
    0 pass / 1 fail, wired into `make bench` / `make bench-gate`.
    """
    with open(GATE_THRESHOLDS) as f:
        thresholds = json.load(f)
    rows = None
    noise = {}
    row_backend = None
    try:
        with open(LAST_DEVICE_ROWS) as f:
            saved = json.load(f)
        if time.time() - saved["time"] < 3600:
            noise = saved.get("noise", {})
            row_backend = saved.get("backend")
            rows = {k: saved[k] for k in thresholds if k in saved}
            # Sample counts ride along for thin-tail discounting.
            rows.update({
                k: v for k, v in saved.items() if k.endswith("_n_samples")
            })
            print(f"gate: using rows from {LAST_DEVICE_ROWS}")
    except (OSError, KeyError, ValueError):
        pass
    if rows is None:
        jax = _jax_setup()
        row_backend = jax.default_backend()
        dev = measure_device(jax, 1_700_000_000_000, samples=6)
        disp = measure_dispatch_pipeline(jax, 1_700_000_000_000)
        rows = {
            "device_batch_us": dev["device_batch_us"],
            "device_us_b1024": dev["small_batch_us"][1024][0],
            "device_us_b256": dev["small_batch_us"][256][0],
            "dispatch_overlap_ratio": dev["device_batch_us"]
            / max(disp["dispatch_batch_us"], 1e-9),
        }
        try:
            # Daemon-spawning rows measure separately-guarded: host
            # weather (a corrupt compile cache, OOM) must cost a SKIP,
            # not the whole verdict.
            ingress_cps, p50, p99, n_lat, steady_rc = measure_service_ingress()
            rows["service_ingress_checks_per_sec"] = ingress_cps
            rows["service_ingress_latency_ms_p50"] = p50
            rows["service_ingress_latency_ms_p99"] = p99
            rows["service_ingress_latency_ms_p50_n_samples"] = n_lat
            rows["service_ingress_latency_ms_p99_n_samples"] = n_lat
            if steady_rc is not None:
                rows["steady_state_recompiles"] = steady_rc
            else:  # absent row -> the gate prints its no-measurement SKIP
                print(
                    "gate steady_state_recompiles: SKIP "
                    "(xla telemetry disabled or listener absent)"
                )
        except Exception as e:  # noqa: BLE001
            print(f"gate service_ingress_checks_per_sec: SKIP (measure failed: {e})")
        try:
            cols_cps = measure_peer_forward("columns")
            classic_cps = measure_peer_forward("classic")
            rows["peer_forward_checks_per_sec"] = cols_cps
            # The ratio is the robust row: both modes measured
            # back-to-back see the same host weather, so a wire-path
            # regression shows even when the absolute numbers swing.
            rows["peer_forward_vs_classic"] = cols_cps / max(classic_cps, 1.0)
        except Exception as e:  # noqa: BLE001 — two-daemon spawn can fail
            print(f"gate peer_forward_checks_per_sec: SKIP (measure failed: {e})")
        noise = {
            f"device_us_b{sb}": dev["small_batch_us"][sb][3]
            for sb in (256, 1024)
        }
    if "ingress_columns_vs_json" not in rows:
        try:
            ic_cols = measure_ingress_columns("columns")
            ic_json = measure_ingress_columns("json")
            rows["ingress_columns_checks_per_sec"] = ic_cols
            # Same-run ratio: both legs hammer identical daemon builds
            # back-to-back, so host weather cancels.
            rows["ingress_columns_vs_json"] = ic_cols / max(ic_json, 1.0)
            print(
                f"gate ingress rows: columnar {ic_cols:.0f} checks/s, "
                f"json {ic_json:.0f} checks/s"
            )
        except Exception as e:  # noqa: BLE001 — daemon spawn can fail
            print(f"gate ingress_columns_vs_json: SKIP (measure failed: {e})")
    if "native_ingress_checks_per_s" not in rows:
        try:
            ni = measure_native_ingress()
            rows["native_ingress_checks_per_s"] = ni["checks_per_s"]
            noise["native_ingress_checks_per_s"] = ni["noise"]
            # ABBA-interleaved ratio: both daemons alive at once, legs
            # alternately driven, so host weather cancels inside each
            # quad and the ratio isolates the native loop itself.
            rows["native_vs_pr8_ratio"] = ni["ratio"]
            noise["native_vs_pr8_ratio"] = ni["ratio_noise"]
            rows["native_ingress_audit_violations"] = ni["audit_violations"]
            if ni["steady_recompiles"] is not None:
                rows["native_ingress_steady_recompiles"] = (
                    ni["steady_recompiles"]
                )
            print(
                f"gate native ingress rows: native {ni['checks_per_s']:.0f} "
                f"checks/s, pr8 {ni['pr8_checks_per_s']:.0f} checks/s, "
                f"ratio {ni['ratio']:.2f}, "
                f"steady_recompiles {ni['steady_recompiles']}, "
                f"audit_violations {ni['audit_violations']}"
            )
        except Exception as e:  # noqa: BLE001 — daemon spawn can fail
            print(f"gate native_ingress_checks_per_s: SKIP (measure failed: {e})")
    if "express_latency_ms_p50" not in rows:
        try:
            xl = measure_express_latency()
            rows["express_latency_ms_p50"] = xl["p50_ms"]
            rows["express_latency_ms_p99"] = xl["p99_ms"]
            rows["express_latency_ms_p50_n_samples"] = xl["n_samples"]
            rows["express_latency_ms_p99_n_samples"] = xl["n_samples"]
            noise["express_latency_ms_p50"] = xl["p50_noise_ms"]
            noise["express_latency_ms_p99"] = xl["noise_ms"]
            rows["express_audit_violations"] = xl["audit_violations"]
            if xl["steady_recompiles"] is not None:
                rows["express_steady_recompiles"] = xl["steady_recompiles"]
            print(
                f"gate express rows: p50 {xl['p50_ms']:.2f}ms, "
                f"p99 {xl['p99_ms']:.2f}ms over {xl['n_samples']} samples "
                f"({xl['checks_per_s']:.0f} checks/s closed-loop, "
                f"{xl['express_frames']} native-express lanes, "
                f"steady_recompiles {xl['steady_recompiles']}, "
                f"audit_violations {xl['audit_violations']})"
            )
        except Exception as e:  # noqa: BLE001 — daemon spawn can fail
            print(f"gate express_latency_ms_p50: SKIP (measure failed: {e})")
    if "global_plane_vs_classic" not in rows:
        try:
            gp_cols = measure_global_plane("columns")
            gp_classic = measure_global_plane("classic")
            rows["global_plane_vs_classic"] = gp_cols[
                "plane_items_per_sec"
            ] / max(gp_classic["plane_items_per_sec"], 1.0)
            print(
                "gate global plane rows: columnar "
                f"bc {gp_cols['broadcast_items_per_sec']:.0f}/s "
                f"hits {gp_cols['forwarded_hits_per_sec']:.0f}/s; classic "
                f"bc {gp_classic['broadcast_items_per_sec']:.0f}/s "
                f"hits {gp_classic['forwarded_hits_per_sec']:.0f}/s"
            )
        except Exception as e:  # noqa: BLE001 — two-daemon spawn can fail
            print(f"gate global_plane_vs_classic: SKIP (measure failed: {e})")
    if "region_plane_vs_classic" not in rows:
        try:
            rp_cols = measure_region_plane("columns")
            rp_classic = measure_region_plane("classic")
            # Same-run ratio: both legs back-to-back against identical
            # subprocess receivers, so host weather cancels.
            rows["region_plane_vs_classic"] = rp_cols / max(rp_classic, 1.0)
            print(
                f"gate region plane rows: columnar {rp_cols:.0f} lanes/s, "
                f"classic {rp_classic:.0f} lanes/s"
            )
        except Exception as e:  # noqa: BLE001 — two-daemon spawn can fail
            print(f"gate region_plane_vs_classic: SKIP (measure failed: {e})")
    if "snapshot_restore_ms" not in rows:
        try:
            snap_row = measure_snapshot()
            rows["snapshot_restore_ms"] = snap_row["restore_s"] * 1e3
            rows["snapshot_dump_ms"] = snap_row["dump_s"] * 1e3
            print(
                f"gate snapshot rows: dump {snap_row['dump_s'] * 1e3:.0f}ms, "
                f"restore {snap_row['restore_s'] * 1e3:.0f}ms at "
                f"{snap_row['lanes']} lanes ({snap_row['bytes']} bytes)"
            )
        except Exception as e:  # noqa: BLE001 — two-daemon spawn can fail
            print(f"gate snapshot_restore_ms: SKIP (measure failed: {e})")
    # The plane-overhead rows are SAME-RUN ratios by definition (every
    # leg interleaved in this process), so they never reuse saved rows;
    # each measure returns its own ratio noise (the per-pair spread)
    # for the noise-adjusted verdict.
    try:
        ratio, off_cps, s0_cps, r_noise = measure_tracing_overhead()
        rows["tracing_overhead_ratio"] = ratio
        noise["tracing_overhead_ratio"] = r_noise
        print(
            f"gate tracing rows: compiled-out {off_cps:.0f} checks/s, "
            f"sample-0 {s0_cps:.0f} checks/s"
        )
    except Exception as e:  # noqa: BLE001 — service spawn can fail
        print(f"gate tracing_overhead_ratio: SKIP (measure failed: {e})")
    # Same rule for the XLA-telemetry overhead ratio (telemetry.py).
    try:
        ratio, off_cps, on_cps, r_noise = measure_xla_telemetry_overhead()
        rows["xla_telemetry_overhead_ratio"] = ratio
        noise["xla_telemetry_overhead_ratio"] = r_noise
        print(
            f"gate xla telemetry rows: off {off_cps:.0f} checks/s, "
            f"on {on_cps:.0f} checks/s"
        )
    except Exception as e:  # noqa: BLE001 — service spawn can fail
        print(f"gate xla_telemetry_overhead_ratio: SKIP (measure failed: {e})")
    # Same rule for the cost-observatory overhead ratio (profiling.py).
    try:
        ratio, off_cps, on_cps, r_noise = measure_profiling_overhead()
        rows["profiling_overhead_ratio"] = ratio
        noise["profiling_overhead_ratio"] = r_noise
        print(
            f"gate profiling rows: compiled-out {off_cps:.0f} checks/s, "
            f"on {on_cps:.0f} checks/s"
        )
    except Exception as e:  # noqa: BLE001 — service spawn can fail
        print(f"gate profiling_overhead_ratio: SKIP (measure failed: {e})")
    # Same rule for the incident-black-box tap (blackbox.py), plus the
    # off-thread bundle-write ceiling and the conservation rider: the
    # ratio only counts if zero audit violations fired during the run.
    try:
        ratio, off_cps, on_cps, r_noise, bb_viol = (
            measure_blackbox_overhead()
        )
        rows["blackbox_overhead_ratio"] = ratio
        noise["blackbox_overhead_ratio"] = r_noise
        rows["blackbox_audit_violations"] = bb_viol
        print(
            f"gate blackbox rows: compiled-out {off_cps:.0f} checks/s, "
            f"on {on_cps:.0f} checks/s, violations {bb_viol}"
        )
    except Exception as e:  # noqa: BLE001 — service spawn can fail
        print(f"gate blackbox_overhead_ratio: SKIP (measure failed: {e})")
    try:
        ms, ring_bytes = measure_blackbox_bundle_write()
        rows["blackbox_bundle_write_ms"] = ms
        print(
            f"gate blackbox bundle write: {ms:.0f}ms for "
            f"{ring_bytes / 1e6:.1f}MB of rings"
        )
    except Exception as e:  # noqa: BLE001 — disk can fail
        print(f"gate blackbox_bundle_write_ms: SKIP (measure failed: {e})")
    failed = []
    for name, spec in thresholds.items():
        if name.startswith("_"):
            continue  # metadata keys (_comment, _updated)
        value = rows.get(name)
        if value is None:
            print(f"gate {name}: SKIP (no fresh measurement)")
            continue
        # Backend-keyed ceilings: the device-microsecond rows are
        # calibrated against the TPU tunnel's measured best; a
        # tunnel-less CPU box measures the same path 10-100x slower
        # through no regression of its own (the PR 9 verify note), so
        # those rows SKIP with the reason named instead of failing the
        # whole gate.
        only = spec.get("only_backend")
        if only:
            if row_backend is None:
                row_backend = _jax_setup().default_backend()
            if row_backend != only:
                print(
                    f"gate {name}: SKIP (backend '{row_backend}' != "
                    f"'{only}': ceiling calibrated on the {only} tunnel; "
                    f"expected on CPU boxes)"
                )
                continue
        # Thin-tail discount: a percentile judged from too few samples
        # is noise shaped like a verdict — rows record n_samples, and
        # specs with min_samples SKIP below it.
        n_min = spec.get("min_samples")
        n_got = rows.get(f"{name}_n_samples")
        if n_min and n_got is not None and n_got < n_min:
            print(
                f"gate {name}: SKIP (thin tail: {n_got} samples "
                f"< min_samples {n_min})"
            )
            continue
        verdict, limit = gate_verdict(value, spec, noise.get(name, 0.0))
        bound = (
            "fail above"
            if ("fail_above_us" in spec or "fail_above" in spec)
            else "fail below"
        )
        n_txt = f" +-{noise[name]:.1f} noise" if noise.get(name) else ""
        print(f"gate {name}: {value:.2f}{n_txt} ({bound} {limit:.2f}) {verdict}"
              + (" (noise straddles the limit)" if verdict == "SKIP" else ""))
        if verdict == "FAIL":
            failed.append(name)
    if failed:
        print(f"gate: REGRESSION in {failed} (see {GATE_THRESHOLDS})")
        return 1
    print("gate: PASS")
    return 0


def main():
    jax = _jax_setup()

    from gubernator_tpu.models.shard import ShardStore
    from gubernator_tpu.types import Algorithm, RateLimitRequest

    rng = np.random.RandomState(42)
    n_keys = 100_000
    batch_size = 131_072
    now = 1_700_000_000_000

    # Zipf-ish mix: 80% of traffic on 10% of keys.
    hot = rng.randint(0, n_keys // 10, size=batch_size)
    cold = rng.randint(0, n_keys, size=batch_size)
    pick_hot = rng.random(batch_size) < 0.8
    key_ids = np.where(pick_hot, hot, cold)

    # ---- headline: overlapped columnar dispatch pipeline -------------
    # Two dispatcher threads ride apply_columns_async's three-stage
    # pipeline: thread B's PREPARE (C++ plan, GIL released) overlaps
    # thread A's fetch/commit, the launch stage fuses same-shape staged
    # batches under backlog, and the launch-time async-copy request
    # overlaps each readback with the next batch's host work.  Values
    # fit int32 so the narrow wire halves bytes both ways.
    store = ShardStore(capacity=300_000)
    keys = [f"bench_account:{k}" for k in key_ids]
    algo = (key_ids % 2).astype(np.int32)  # mixed token/leaky
    behavior = np.zeros(batch_size, np.int32)
    hits = np.ones(batch_size, np.int64)
    limit = np.full(batch_size, 1_000_000, np.int64)
    duration = np.full(batch_size, 3_600_000, np.int64)

    def dispatch(i):
        return store.apply_columns_async(
            keys, algo, behavior, hits, limit, duration, now + i
        )

    dispatch(0).result()  # warmup: compile + table fill
    dispatch(1).result()

    import threading as _threading

    n_disp, iters = 2, 4

    def disp_worker(base):
        from collections import deque as _dq

        pending = _dq()
        for i in range(iters):
            pending.append(dispatch(base + i))
            if len(pending) >= 2:
                pending.popleft().result()
        while pending:
            pending.popleft().result()

    def disp_epoch(base):
        ts = [
            _threading.Thread(target=disp_worker, args=(base + t * iters,))
            for t in range(n_disp)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    disp_epoch(2)  # warm the fused-launch programs this shape fuses into
    # Best of 3 epochs: the remote-device tunnel's throughput swings
    # ~2x between runs; the fastest epoch is the least-contended view
    # of the software's own cost.
    columnar_cps, step = 0.0, 2 + n_disp * iters
    store.take_pipeline_stats()  # reset the depth high-water mark
    from gubernator_tpu import saturation as _saturation

    _saturation.lane_util.take()  # reset: measure the headline epochs only
    for _ in range(3):
        t0 = time.perf_counter()
        disp_epoch(step)
        dt = time.perf_counter() - t0
        step += n_disp * iters
        columnar_cps = max(columnar_cps, batch_size * iters * n_disp / dt)
    stage_stats, _, pipeline_depth_hwm = store.take_pipeline_stats()
    util_lanes, util_padded, util_launches = _saturation.lane_util.take()
    pipeline_stage_ms = {
        stage: round(total / max(count, 1) * 1000.0, 3)
        for stage, (count, total, _mx) in stage_stats.items()
    }

    # Sequential (non-pipelined) dispatch -> own-result round trips:
    # the latency one batch actually experiences.  Median of a few
    # samples — too few for a meaningful p99.
    lat = []
    for i in range(5):
        t_b = time.perf_counter()
        dispatch(100 + i).result()
        lat.append(time.perf_counter() - t_b)
    lat.sort()
    batch_latency_ms = percentile(lat, 0.50) * 1000.0
    # Occupancy rows from the headline store (host tables only — the
    # same zero-extra-dispatch read /debug/status serves).
    occupancy_used = store.size()
    occupancy_capacity = store.capacity
    occupancy_evictions = int(store.table.evictions)

    # ---- device-only kernel timing -----------------------------------
    dev = measure_device(jax, now)
    disp = measure_dispatch_pipeline(jax, now)
    device_batch_us = dev["device_batch_us"]
    device_cps = dev["device_cps"]
    small_batch_us = dev["small_batch_us"]
    dispatch_p50 = dev["dispatch_p50"]
    dispatch_p99 = dev["dispatch_p99"]
    # The dispatch row the pipeline actually pays per batch (staged
    # packed wire, fused launch) vs the chip's own time: host dispatch
    # cost is hidden when this ratio approaches 1.
    dispatch_batch_us = disp["dispatch_batch_us"]
    dispatch_overlap_ratio = device_batch_us / max(dispatch_batch_us, 1e-9)
    # Save the device + overlap rows NOW: the service/peer measurements
    # below spawn daemons and can die to host weather (a corrupt
    # compile cache, OOM on a loaded box) — a crash there must not
    # cost the gate its stable same-run rows.
    _save_device_rows(dev, {"dispatch_overlap_ratio": dispatch_overlap_ratio})
    zipf = measure_device_zipf(jax, now)

    # Per-leg XLA compile accounting (telemetry.py): compiles in THIS
    # process attributed to each measurement leg — subprocess-daemon
    # legs compile in their own processes and report 0 here.
    from gubernator_tpu import telemetry as _telemetry

    xla_compiles_per_leg = {}
    # Baseline 0, not compile_count(): the headline/device legs above
    # already ran, and their compiles (everything since process start)
    # belong to the first row — a baseline captured HERE would always
    # read that row as 0.
    _leg_cc = [0]

    def _leg(name):
        cur = _telemetry.compile_count()
        xla_compiles_per_leg[name] = cur - _leg_cc[0]
        _leg_cc[0] = cur

    _leg("headline_and_device")

    # ---- service-tier columnar ingress -------------------------------
    service_cps, svc_p50, svc_p99, svc_lat_n, steady_recompiles = (
        measure_service_ingress()
    )
    _leg("service_ingress")

    # ---- public ingress: columnar front door vs classic JSON ---------
    ingress_columns_cps = measure_ingress_columns("columns")
    ingress_json_cps = measure_ingress_columns("json")
    ingress_columns_ratio = ingress_columns_cps / max(ingress_json_cps, 1.0)
    _leg("ingress_columns")

    # ---- native service loop vs the PR 8 Python-assembled edge -------
    native_ingress = measure_native_ingress()
    native_vs_pr8 = native_ingress["ratio"]
    _leg("native_ingress")

    # ---- express lane: shallow-queue singleton latency ---------------
    express_lat = measure_express_latency()
    _leg("express_latency")

    # ---- peer hop: loopback two-daemon forward (CPU-pinned) ----------
    peer_forward_cps = measure_peer_forward("columns")
    peer_forward_classic_cps = measure_peer_forward("classic")

    # ---- GLOBAL replication plane: loopback broadcast + hit forward --
    global_plane = measure_global_plane("columns")
    global_plane_classic = measure_global_plane("classic")
    global_plane_ratio = global_plane["plane_items_per_sec"] / max(
        global_plane_classic["plane_items_per_sec"], 1.0
    )

    # ---- multi-region federation plane: loopback cross-region sends --
    region_plane_cps = measure_region_plane("columns")
    region_plane_classic_cps = measure_region_plane("classic")
    region_plane_ratio = region_plane_cps / max(region_plane_classic_cps, 1.0)
    _leg("peer_and_global_plane")

    # Re-save with the ingress + peer-forward rows so --gate covers
    # end-to-end service-path regressions, not just the device kernel
    # (round-4 verdict: the headline regressed ungated across rounds).
    _save_device_rows(dev, {
        "service_ingress_checks_per_sec": service_cps,
        "service_ingress_latency_ms_p50": svc_p50,
        "service_ingress_latency_ms_p99": svc_p99,
        "service_ingress_latency_ms_p50_n_samples": svc_lat_n,
        "service_ingress_latency_ms_p99_n_samples": svc_lat_n,
        "peer_forward_checks_per_sec": peer_forward_cps,
        "peer_forward_vs_classic": (
            peer_forward_cps / max(peer_forward_classic_cps, 1.0)
        ),
        "ingress_columns_checks_per_sec": ingress_columns_cps,
        "ingress_columns_vs_json": ingress_columns_ratio,
        "native_ingress_checks_per_s": native_ingress["checks_per_s"],
        "native_vs_pr8_ratio": native_vs_pr8,
        "native_ingress_audit_violations": native_ingress["audit_violations"],
        "express_latency_ms_p50": express_lat["p50_ms"],
        "express_latency_ms_p99": express_lat["p99_ms"],
        "express_latency_ms_p50_n_samples": express_lat["n_samples"],
        "express_latency_ms_p99_n_samples": express_lat["n_samples"],
        "express_audit_violations": express_lat["audit_violations"],
        **({"express_steady_recompiles": express_lat["steady_recompiles"]}
           if express_lat["steady_recompiles"] is not None else {}),
        "extra_noise": {
            "native_ingress_checks_per_s": native_ingress["noise"],
            "native_vs_pr8_ratio": native_ingress["ratio_noise"],
            "express_latency_ms_p50": express_lat["p50_noise_ms"],
            "express_latency_ms_p99": express_lat["noise_ms"],
        },
        **({"native_ingress_steady_recompiles":
            native_ingress["steady_recompiles"]}
           if native_ingress["steady_recompiles"] is not None else {}),
        "global_plane_vs_classic": global_plane_ratio,
        "region_plane_vs_classic": region_plane_ratio,
        "dispatch_overlap_ratio": dispatch_overlap_ratio,
        # None (unobservable: telemetry off / listener absent) is kept
        # out of the saved rows so --gate SKIPs instead of passing a
        # blind 0 through the ==0 ceiling.
        **({"steady_state_recompiles": steady_recompiles}
           if steady_recompiles is not None else {}),
    })

    # ---- secondary: request-object path ------------------------------
    def make_batch(salt):
        return [
            RateLimitRequest(
                name="bench",
                unique_key=f"account:{(k + salt) % n_keys}",
                hits=1,
                limit=1_000_000,
                duration=3_600_000,
                algorithm=Algorithm.TOKEN_BUCKET if (k + salt) % 2 == 0 else Algorithm.LEAKY_BUCKET,
            )
            for k in key_ids
        ]

    store2 = ShardStore(capacity=200_000)
    store2.apply(make_batch(0), now)
    store2.apply(make_batch(1), now + 1)
    iters2 = 4
    t0 = time.perf_counter()
    for i in range(iters2):
        store2.apply(make_batch(i + 2), now + 2 + i)
    object_cps = batch_size * iters2 / (time.perf_counter() - t0)

    value = columnar_cps
    baseline = 2000.0  # reference single-node req/s (README.md:96-100)
    row = (
            {
                "metric": "rate_limit_checks_per_sec",
                "value": round(value, 1),
                "unit": "checks/s",
                "vs_baseline": round(value / baseline, 2),
                "object_path_checks_per_sec": round(object_cps, 1),
                "service_ingress_checks_per_sec": round(service_cps, 1),
                "service_ingress_latency_ms_p50": round(svc_p50, 2),
                "service_ingress_latency_ms_p99": round(svc_p99, 2),
                "service_ingress_latency_n_samples": svc_lat_n,
                "service_ingress_includes_tunnel_rtt": True,
                # XLA telemetry rows (telemetry.py): compiles during the
                # measured ingress epoch (0 = no shape churn in steady
                # state, the ceiling `make bench-gate` enforces) and the
                # per-leg compile counts of this process.
                "steady_state_recompiles": steady_recompiles,
                "xla_compiles_per_leg": xla_compiles_per_leg,
                "ingress_columns_checks_per_sec": round(
                    ingress_columns_cps, 1
                ),
                "ingress_json_checks_per_sec": round(ingress_json_cps, 1),
                "ingress_columns_vs_json": round(ingress_columns_ratio, 2),
                "native_ingress_checks_per_s": round(
                    native_ingress["checks_per_s"], 1
                ),
                "native_pr8_checks_per_s": round(
                    native_ingress["pr8_checks_per_s"], 1
                ),
                "native_vs_pr8_ratio": round(native_vs_pr8, 2),
                "native_ingress_steady_recompiles": (
                    native_ingress["steady_recompiles"]
                ),
                "native_ingress_audit_violations": (
                    native_ingress["audit_violations"]
                ),
                # Express lane (PR 14): closed-loop singleton
                # NO_BATCHING latency over the real wire — the
                # interactive floor the lane exists to move.
                "express_latency_ms_p50": round(express_lat["p50_ms"], 3),
                "express_latency_ms_p99": round(express_lat["p99_ms"], 3),
                "express_latency_n_samples": express_lat["n_samples"],
                "express_closed_loop_checks_per_s": round(
                    express_lat["checks_per_s"], 1
                ),
                "express_native_lanes": express_lat["express_frames"],
                "express_steady_recompiles": (
                    express_lat["steady_recompiles"]
                ),
                "express_audit_violations": (
                    express_lat["audit_violations"]
                ),
                "peer_forward_checks_per_sec": round(peer_forward_cps, 1),
                "peer_forward_classic_checks_per_sec": round(
                    peer_forward_classic_cps, 1
                ),
                "peer_forward_vs_classic": round(
                    peer_forward_cps / max(peer_forward_classic_cps, 1.0), 2
                ),
                "global_broadcast_items_per_sec": round(
                    global_plane["broadcast_items_per_sec"], 1
                ),
                "global_forwarded_hits_per_sec": round(
                    global_plane["forwarded_hits_per_sec"], 1
                ),
                "global_broadcast_classic_items_per_sec": round(
                    global_plane_classic["broadcast_items_per_sec"], 1
                ),
                "global_forwarded_hits_classic_per_sec": round(
                    global_plane_classic["forwarded_hits_per_sec"], 1
                ),
                "global_plane_vs_classic": round(global_plane_ratio, 2),
                "region_plane_lanes_per_sec": round(region_plane_cps, 1),
                "region_plane_classic_lanes_per_sec": round(
                    region_plane_classic_cps, 1
                ),
                "region_plane_vs_classic": round(region_plane_ratio, 2),
                "batch_size": batch_size,
                "batch_latency_ms_median": round(batch_latency_ms, 2),
                "batch_latency_n_samples": len(lat),
                # Saturation plane rows (PR 6): occupancy + lane
                # utilization of the headline run, and the always-on
                # per-phase attribution snapshot (what /debug/latency
                # serves in a live daemon).
                "store_occupancy_used": occupancy_used,
                "store_occupancy_capacity": occupancy_capacity,
                "store_occupancy_evictions": occupancy_evictions,
                "lane_utilization_ratio": round(
                    util_lanes / max(util_padded, 1), 4
                ),
                "lane_utilization_launches": util_launches,
                "attribution_ms_p99": {
                    phase: snap["p99_ms"]
                    for phase, snap in _saturation.phase_snapshot().items()
                    if phase.startswith(("dispatch.", "batch.", "queue."))
                },
                "device_batch_us": round(device_batch_us, 1),
                "device_checks_per_sec": round(device_cps, 1),
                "device_vs_northstar_50m": round(device_cps / 50e6, 4),
                "device_zipf_batch_us": round(zipf["device_zipf_batch_us"], 1),
                "device_zipf_checks_per_sec": round(zipf["device_zipf_cps"], 1),
                "device_zipf_vs_northstar_50m": round(zipf["device_zipf_cps"] / 50e6, 4),
                "device_zipf_total_capacity": zipf["total_capacity"],
                "device_zipf_write_fraction": round(zipf["zipf_write_fraction"], 4),
                "device_zipf_n_rounds": zipf["zipf_n_rounds"],
                "dispatch_batch_us_incl_tunnel": round(dispatch_batch_us, 1),
                "dispatch_overlap_ratio": round(dispatch_overlap_ratio, 3),
                "dispatch_solo_batch_us": round(
                    disp["dispatch_solo_batch_us"], 1
                ),
                "dispatch_fuse": disp["dispatch_fuse"],
                "dispatch_batch32_us": round(dev["dispatch_batch_us"], 1),
                "dispatch_pipeline_depth_hwm": pipeline_depth_hwm,
                "pipeline_stage_ms_mean": pipeline_stage_ms,
                "device_us_b256": round(small_batch_us[256][0], 1),
                "device_us_b256_worst": round(small_batch_us[256][1], 1),
                "device_us_b256_below_floor": small_batch_us[256][2],
                "device_us_b256_noise_us": round(small_batch_us[256][3], 1),
                "device_us_b1024": round(small_batch_us[1024][0], 1),
                "device_us_b1024_worst": round(small_batch_us[1024][1], 1),
                "device_us_b1024_below_floor": small_batch_us[1024][2],
                "device_us_b1024_noise_us": round(small_batch_us[1024][3], 1),
                "device_us_b4096": round(small_batch_us[4096][0], 1),
                "device_us_b4096_worst": round(small_batch_us[4096][1], 1),
                "device_us_b4096_below_floor": small_batch_us[4096][2],
                "device_us_b4096_noise_us": round(small_batch_us[4096][3], 1),
                "dispatch_latency_ms_p50": round(dispatch_p50, 2),
                "dispatch_latency_ms_p99": round(dispatch_p99, 2),
                "dispatch_latency_n_samples": dev["dispatch_lat_n_samples"],
                "dispatch_latency_includes_tunnel_rtt": True,
            }
    )
    print(json.dumps(row))
    # Bench-history trend record (scripts/bench_trend.py reads these).
    append_history(row)


if __name__ == "__main__":
    if "--gate" in sys.argv:
        sys.exit(gate())
    main()
