"""Benchmark: end-to-end rate-limit check throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference reports > 2,000 requests/s on a single
production node with batching (README.md:96-100; BASELINE.md).  Each
value here is a full rate-limit check (validate -> key->slot resolve ->
vectorized kernel -> response), measured steady-state through the
public ShardStore path over a Zipf-ish key mix (hot keys + long tail),
which mirrors BASELINE.json config 2.
"""

import json
import time

import numpy as np


def main():
    from gubernator_tpu.models.shard import ShardStore
    from gubernator_tpu.types import Algorithm, RateLimitRequest

    rng = np.random.RandomState(42)
    n_keys = 100_000
    batch_size = 8192
    store = ShardStore(capacity=200_000)
    now = 1_700_000_000_000

    # Zipf-ish mix: 80% of traffic on 10% of keys.
    hot = rng.randint(0, n_keys // 10, size=batch_size)
    cold = rng.randint(0, n_keys, size=batch_size)
    pick_hot = rng.random(batch_size) < 0.8
    key_ids = np.where(pick_hot, hot, cold)

    def make_batch(salt):
        return [
            RateLimitRequest(
                name="bench",
                unique_key=f"account:{(k + salt) % n_keys}",
                hits=1,
                limit=1_000_000,
                duration=3_600_000,
                algorithm=Algorithm.TOKEN_BUCKET if (k + salt) % 2 == 0 else Algorithm.LEAKY_BUCKET,
            )
            for k in key_ids
        ]

    # Warmup (compile + table fill).
    store.apply(make_batch(0), now)
    store.apply(make_batch(1), now + 1)

    checks = 0
    t0 = time.perf_counter()
    rounds = 8
    for i in range(rounds):
        batch = make_batch(i % 4)
        store.apply(batch, now + 2 + i)
        checks += len(batch)
    dt = time.perf_counter() - t0

    value = checks / dt
    baseline = 2000.0  # reference single-node req/s (README.md:96-100)
    print(
        json.dumps(
            {
                "metric": "rate_limit_checks_per_sec",
                "value": round(value, 1),
                "unit": "checks/s",
                "vs_baseline": round(value / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
